"""Repo-specific static-analysis rule families.

Each rule machine-checks one of the serving stack's written-in-prose
contracts (docs/ANALYSIS.md maps every rule to the contract it
guards).  Rules are AST visitors over one module at a time; they are
deliberately narrow — a rule that cries wolf gets suppressed into
uselessness, so each one flags only the patterns that have actually
bitten (or would bite) this codebase:

- RNG-DET    position-keyed RNG discipline in the serving-critical
             paths: no ``jax.random.split`` chains, no fresh
             ``PRNGKey`` that isn't immediately folded — a split
             chain makes token values depend on the draw SCHEDULE,
             which co-tenancy changes (docs/SERVING.md RNG contract).
- LOCK-HOLD  no unbounded blocking inside a ``with <...lock>`` body:
             ``time.sleep``, untimed ``.wait()``/``.get()``/
             ``.join()``, socket/HTTP I/O, or a method-form
             ``.block_until_ready()`` under a serving lock turns one
             slow caller into a server-wide stall.  The functional
             ``jax.block_until_ready(x)`` spelling is the sanctioned
             step-sync idiom and is allowed.
- JIT-PURITY no trace-time-frozen impurity inside jitted functions:
             ``time.*`` clocks, ``np.random.*`` / stdlib ``random.*``
             draws, and ``global`` mutation all execute ONCE at trace
             time and silently become constants; static_argnums /
             static_argnames targets must be hashable.
- HOST-SYNC  implicit device->host syncs in the engine step hot path
             (``np.asarray``/``float``/``int`` directly on a jax
             call, ``.tolist()``/``.item()``): every one is a hidden
             ``block_until_ready`` that serializes the decode loop.
             Explicit ``jax.device_get(...)`` is the sanctioned
             spelling.
- JIT-DEADLINE no ``time.*`` calls AT ALL inside jitted programs:
             lifecycle control (deadline/cancel/preempt decisions)
             is host-side scheduling — a deadline comparison traced
             into a step program evaluates once and never fires
             again.  Broader than JIT-PURITY's clock list on
             purpose; the two share one jitted-body collector.
- EXC-SWALLOW ``except Exception: pass`` (body is ONLY ``pass`` /
             ``continue``) drops errors on the floor; best-effort
             teardown must say so in the baseline, everything else
             must at least log.
- TIME-TRUTH host-clock deltas over ASYNC jax dispatch in serving/
             and benchmarks/: a ``t0 = time.perf_counter()`` ...
             ``... - t0`` pair with a jax call between them and no
             ``jax.block_until_ready`` / ``jax.device_get`` sync in
             the span measures DISPATCH time, not device time — jax
             returns futures, so the delta is a lie that understates
             real work by the whole async tail (the flight recorder
             and its trace attribution exist because of exactly this
             class of timing; serving/profiling.py).
- SHARD-LEAK  unsharded host-array placement in the serving layer:
             a single-argument ``jax.device_put(x)`` (uncommitted —
             lands on the default device, and fed to a mesh-compiled
             step program it forces a transfer/gather on EVERY call),
             or a ``jnp.zeros``-family allocation assigned straight
             to KV-pool state (``_stacked``/``_pool``/...) outside
             the mesh-aware ``_alloc*``/``_ensure*`` helpers that
             commit pools to their NamedShardings at birth.

- RETRY-BACKOFF unbounded ``while True`` retry loops around jax or
             socket calls in serving/ — a broad handler that loops
             again without bound turns a permanent failure into an
             invisible infinite spin; the sanctioned spelling is the
             shared bounded jittered-backoff ``RetryPolicy``
             (serving/recovery.py), escalating once retries exhaust.

- TIER-XFER  device<->host transfers of PAGE-POOL payloads in
             serving/ outside the sanctioned tiered-memory helpers
             (``spill_pages`` / ``rematerialize`` / ``materialize``
             / ``_alloc_pool`` / ``scatter_cache``): a stray
             ``jax.device_put``/``device_get`` whose operand touches
             pool/page state moves page-sized KV across PCIe on
             whatever path it sits — on the step path that is a
             silent TTFT cliff nobody profiled for (the host tier
             spills on page-pressure reclaim and re-materializes at
             prefix-hit admission, NEVER per step).

Suppression: ``# ptpu: ignore[RULE-A,RULE-B]`` on the flagged line or
the line directly above silences those rules for that line;
``# ptpu: ignore[*]`` silences everything.  Suppressions are for
findings whose justification is local to the code; findings whose
justification is historical (legacy reference paths) belong in the
committed baseline (analysis/baseline.py) with a per-entry
justification.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "Rule", "ALL_RULES", "RULE_IDS", "dotted_name"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``key()`` deliberately excludes the line number: baselines match
    on (rule, path, enclosing function, source text), so edits above
    a baselined finding don't invalidate the whole file's entries.
    """

    rule: str
    path: str       # posix-style path relative to the checked root
    line: int       # 1-based, for humans and editors
    func: str       # enclosing def chain, or "<module>"
    code: str       # stripped source line
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.func, self.code)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.code)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.func}] {self.message}\n    {self.code}")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _src_line(lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


class Rule:
    """One rule family.  Subclasses set ``id`` and implement
    ``applies_to`` (path scoping) and ``check``."""

    id: str = ""
    message: str = ""

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, tree: ast.Module, lines: Sequence[str],
              relpath: str) -> List[Finding]:
        raise NotImplementedError


class _ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the enclosing function-def chain."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def func(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def _in_serving(relpath: str) -> bool:
    return "/serving/" in "/" + relpath


# -- RNG-DET ----------------------------------------------------------------


class RngDetRule(Rule):
    """Position-keyed RNG only in serving-critical paths.

    Flags ``jax.random.split`` (any alias ending in ``.split`` whose
    root module is a jax random namespace) and fresh ``PRNGKey(...)``
    construction, UNLESS the key is immediately position-keyed: the
    ``PRNGKey`` call sits inside a ``fold_in(...)`` argument, or is
    assigned to a name that is passed to ``fold_in`` within the same
    function.  Guards the contract that a stream's i-th token key is
    ``fold_in(fold_in(PRNGKey(seed), row), i)`` — a function of the
    request alone — so co-tenancy and admission order can never
    change sampled tokens (docs/SERVING.md)."""

    id = "RNG-DET"

    _SPLIT = re.compile(r"(^|\.)(random|jrandom)\.split$|^jrandom\.split$")

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath) or \
            relpath.endswith("models/generate.py")

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func)
                if name is not None:
                    if rule._SPLIT.search(name):
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            "jax.random.split chains make token "
                            "values depend on the draw schedule; use "
                            "position-keyed fold_in "
                            "(sample_stream_keys)"))
                    elif name.endswith("PRNGKey") and \
                            not self._folded(node):
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            "fresh PRNGKey outside a fold_in: "
                            "serving-path draws must be "
                            "position-keyed (fold_in(PRNGKey(seed), "
                            "row) ... fold_in(base, index))"))
                self.generic_visit(node)

            def _folded(self, node) -> bool:
                # Only fold_in calls in the SAME enclosing function
                # count (module-wide matching would let any unrelated
                # fold_in elsewhere in the file launder a fresh key).
                local = [c for c in self._fold_calls
                         if self._fn_of.get(id(c))
                         is self._fn_of.get(id(node))]
                # (a) nested directly inside a fold_in(...) call
                for anc_call in local:
                    for arg in ast.walk(anc_call):
                        if arg is node:
                            return True
                # (b) assigned to a name folded in the same function
                tgt = self._assign_target(node)
                if tgt is not None:
                    for call in local:
                        for arg in call.args:
                            if isinstance(arg, ast.Name) and \
                                    arg.id == tgt:
                                return True
                return False

            def _assign_target(self, node) -> Optional[str]:
                parent = self._parents.get(node)
                if isinstance(parent, ast.Assign) and \
                        len(parent.targets) == 1 and \
                        isinstance(parent.targets[0], ast.Name):
                    return parent.targets[0].id
                return None

        v = V()
        # Pre-pass: every fold_in call, a child->parent map, and each
        # node's enclosing FunctionDef (lambdas don't open a scope —
        # a fold_in inside a vmapped lambda still belongs to the def
        # that wrote it), so the "immediately folded" exemption can
        # look up and sideways WITHIN one function only.
        v._fold_calls = [
            n for n in ast.walk(tree)
            if isinstance(n, ast.Call)
            and (dotted_name(n.func) or "").endswith("fold_in")]
        v._parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                v._parents[child] = parent

        def fn_of(n):
            n = v._parents.get(n)
            while n is not None and not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                n = v._parents.get(n)
            return n

        v._fn_of = {id(n): fn_of(n) for n in ast.walk(tree)}
        v.visit(tree)
        return findings


# -- LOCK-HOLD --------------------------------------------------------------


_LOCK_NAME = re.compile(r"(^|_)lock$")

_SOCKET_IO = {"create_connection", "urlopen", "recv", "accept",
              "connect", "sendall", "getresponse", "request"}


class LockHoldRule(Rule):
    """No unbounded blocking inside a ``with <...lock>`` body.

    A serving lock (``device_lock``, ``_lock``, ``_stats_lock``,
    ``_prefix_lock``, anything matching ``*_lock``) serializes every
    handler thread behind its holder: an untimed wait under one turns
    a single slow caller into a server-wide stall, and an inversion-
    prone sleep is a deadlock seed.  Flags, inside such a body (not
    descending into nested function defs, which run later):
    ``time.sleep``; ``.wait()`` / ``.get()`` / ``.join()`` with no
    timeout; socket/HTTP I/O calls; method-form
    ``x.block_until_ready()``.  The functional
    ``jax.block_until_ready(x)`` used to fence a device step is the
    sanctioned sync idiom and is NOT flagged — the step sync is why
    the lock is held at all."""

    id = "LOCK-HOLD"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_With(self, node):
                held = None
                for item in node.items:
                    name = dotted_name(item.context_expr)
                    if name is None and \
                            isinstance(item.context_expr, ast.Call):
                        name = dotted_name(item.context_expr.func)
                    last = (name or "").rsplit(".", 1)[-1]
                    if _LOCK_NAME.search(last):
                        held = last
                        break
                if held is not None:
                    for stmt in node.body:
                        self._scan(stmt, held)
                self.generic_visit(node)

            visit_AsyncWith = visit_With

            def _scan(self, node, held: str) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return          # runs later, not under the lock
                if isinstance(node, ast.Call):
                    self._check_call(node, held)
                for child in ast.iter_child_nodes(node):
                    self._scan(child, held)

            @staticmethod
            def _none_const(a) -> bool:
                return isinstance(a, ast.Constant) and a.value is None

            @staticmethod
            def _true_const(a) -> bool:
                return isinstance(a, ast.Constant) and a.value is True

            def _untimed(self, node: ast.Call, tail: str) -> bool:
                """True when this wait/join/get/wait_for call blocks
                without a bound.  A positional arg is only a timeout
                where the stdlib signature puts one — ``q.get(True)``
                and ``t.join(None)`` are still unbounded."""
                kw = {k.arg: k.value for k in node.keywords}
                timeout = kw.get("timeout")
                if timeout is not None and \
                        not self._none_const(timeout):
                    return False
                if tail in ("wait", "join"):
                    # signature: (timeout=None)
                    return not node.args \
                        or self._none_const(node.args[0])
                if tail == "wait_for":
                    # signature: (predicate, timeout=None)
                    return len(node.args) < 2 \
                        or self._none_const(node.args[1])
                # get: signature (block=True, timeout=None) — only
                # the blocking forms count (q.get(), q.get(True),
                # block=True); d.get(key[, default]) never matches.
                # (acquire shares the (blocking, timeout) shape but
                # has its own check: see _unbounded_acquire.)
                if len(node.args) >= 2 and \
                        not self._none_const(node.args[1]):
                    return False
                blocking = (not node.args and "block" not in kw) \
                    or (node.args and self._true_const(node.args[0])) \
                    or self._true_const(kw.get("block"))
                return bool(blocking)

            @staticmethod
            def _neg_num_const(a) -> bool:
                """A literal negative number (parses as USub over a
                Constant): acquire's spelled-out block-forever."""
                if isinstance(a, ast.UnaryOp) \
                        and isinstance(a.op, ast.USub) \
                        and isinstance(a.operand, ast.Constant):
                    v = a.operand.value
                    return isinstance(v, (int, float)) \
                        and not isinstance(v, bool)
                return False

            def _unbounded_acquire(self, node: ast.Call) -> bool:
                """Lock.acquire(blocking=True, timeout=-1): blocking
                with no timeout.  ``acquire(False)`` (try-lock) and
                an explicit non-literal-negative timeout are bounded
                — but ``timeout=-1`` (or ``acquire(True, -1)``) is
                the stdlib's SPELLED-OUT block-forever and stays
                flagged; a variable timeout gets the benefit of the
                doubt like the rest of the rule."""
                kw = {k.arg: k.value for k in node.keywords}
                if "timeout" in kw:
                    t = kw["timeout"]
                    return self._none_const(t) \
                        or self._neg_num_const(t)
                if len(node.args) >= 2:
                    t = node.args[1]
                    return self._none_const(t) \
                        or self._neg_num_const(t)
                blocking = (not node.args and "blocking" not in kw) \
                    or (node.args
                        and self._true_const(node.args[0])) \
                    or self._true_const(kw.get("blocking"))
                return bool(blocking)

            def _check_call(self, node: ast.Call, held: str) -> None:
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                msg = None
                if name == "time.sleep":
                    msg = "time.sleep while holding"
                elif tail in ("wait", "get", "join", "wait_for") and \
                        isinstance(node.func, ast.Attribute) and \
                        self._untimed(node, tail):
                    msg = f"untimed .{tail}() while holding"
                elif tail == "acquire" and \
                        isinstance(node.func, ast.Attribute) and \
                        _LOCK_NAME.search(
                            (dotted_name(node.func.value) or "")
                            .rsplit(".", 1)[-1]) and \
                        self._unbounded_acquire(node):
                    # Nested blocking lock acquisition under a held
                    # lock is the lock-order-inversion seed the
                    # cancellation/eviction paths must never plant:
                    # `with a_lock: b_lock.acquire()` deadlocks
                    # against any thread doing the reverse.
                    msg = "untimed nested lock .acquire() while " \
                          "holding"
                elif tail == "block_until_ready" and \
                        isinstance(node.func, ast.Attribute) and \
                        dotted_name(node.func.value) not in ("jax",):
                    msg = ("method-form .block_until_ready() while "
                           "holding")
                elif tail in _SOCKET_IO and (
                        name.startswith(("socket.", "requests.",
                                         "urllib.", "http."))
                        or tail in ("urlopen", "create_connection")):
                    msg = f"socket/HTTP I/O ({tail}) while holding"
                if msg is not None:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"{msg} {held}: one slow caller stalls every "
                        f"thread queued on the lock — bound it with a "
                        f"timeout or move it outside the critical "
                        f"section"))

        V().visit(tree)
        return findings


# -- JIT-PURITY -------------------------------------------------------------


_IMPURE_CALLS = re.compile(
    r"^(time\.(time|perf_counter|monotonic)"
    r"|np\.random\.\w+|numpy\.random\.\w+"
    r"|random\.\w+)$")


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _collect_jitted(tree: ast.Module):
    """Every jit-wrapped body in a module: decorated defs,
    ``jax.jit(lambda ...)``, and ``jax.jit(fn_name)`` with the name
    resolved LEXICALLY (scope chain from the call site — without
    this, ``jax.jit(step)`` inside a builder method resolves to an
    unrelated same-named METHOD elsewhere in the module and flags
    code that never traces).  Returns ``(jitted_bodies, jit_calls)``:
    ``jitted_bodies`` is ``[(body node, label)]`` deduplicated,
    ``jit_calls`` is ``[(jit Call node, resolved def or None)]`` for
    call-site checks (static_argnums hashability).  Shared by
    JIT-PURITY and JIT-DEADLINE so the two rules can never disagree
    about what "inside a jitted program" means."""
    parents: Dict[ast.AST, ast.AST] = {}
    for p in ast.walk(tree):
        for c in ast.iter_child_nodes(p):
            parents[c] = p
    scopes: Dict[ast.AST, Dict[str, ast.FunctionDef]] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            s = parents.get(n)
            while s is not None and not isinstance(
                    s, (ast.Module, ast.FunctionDef,
                        ast.AsyncFunctionDef, ast.ClassDef)):
                s = parents.get(s)
            scopes.setdefault(s, {})[n.name] = n

    def resolve(call: ast.AST, name: str):
        """Innermost def named ``name`` visible from ``call``."""
        s = parents.get(call)
        while s is not None:
            if isinstance(s, (ast.Module, ast.FunctionDef,
                              ast.AsyncFunctionDef, ast.ClassDef)):
                d = scopes.get(s, {}).get(name)
                if d is not None:
                    return d
            s = parents.get(s)
        return None

    jitted_bodies: List[Tuple[ast.AST, str]] = []
    jit_calls: List[Tuple[ast.Call, Optional[ast.FunctionDef]]] = []
    seen: Set[int] = set()

    def add(node, label):
        if id(node) not in seen:
            seen.add(id(node))
            jitted_bodies.append((node, label))

    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                if _is_jax_jit(dec):
                    add(n, n.name)
                elif isinstance(dec, ast.Call) and (
                        _is_jax_jit(dec.func)
                        or (dotted_name(dec.func) or "").endswith(
                            "partial")
                        and dec.args
                        and _is_jax_jit(dec.args[0])):
                    add(n, n.name)
        elif isinstance(n, ast.Call) and _is_jax_jit(n.func):
            fn = None
            if n.args:
                target = n.args[0]
                if isinstance(target, ast.Lambda):
                    add(target, "<lambda>")
                elif isinstance(target, ast.Name):
                    fn = resolve(n, target.id)
                    if fn is not None:
                        add(fn, target.id)
            jit_calls.append((n, fn))
    return jitted_bodies, jit_calls


class JitPurityRule(Rule):
    """No trace-time impurity inside jitted functions.

    A ``jax.jit``-wrapped function's Python body runs ONCE, at trace
    time: ``time.time()`` / ``np.random.*`` / stdlib ``random.*``
    results are baked into the compiled program as constants, and
    ``global`` writes happen once per compile, not per call — all
    silent wrong-answer bugs.  Also checks that
    ``static_argnums``/``static_argnames`` targets are hashable by
    construction (an unhashable static arg fails at call time, far
    from the jit site): a targeted parameter whose default is a
    list/dict/set literal is flagged."""

    id = "JIT-PURITY"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        jitted_bodies, jit_calls = _collect_jitted(tree)
        for call, fn in jit_calls:
            self._check_static_args(call, fn, lines, relpath,
                                    findings)

        for body, label in jitted_bodies:
            for node in ast.walk(body):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    if _IMPURE_CALLS.match(name) and \
                            not name.startswith(("jax.random.",
                                                 "jrandom.")):
                        findings.append(Finding(
                            self.id, relpath, node.lineno, label,
                            _src_line(lines, node.lineno),
                            f"{name}() inside a jitted function runs "
                            f"once at TRACE time and is baked into "
                            f"the program as a constant"))
                elif isinstance(node, ast.Global):
                    findings.append(Finding(
                        self.id, relpath, node.lineno, label,
                        _src_line(lines, node.lineno),
                        "global mutation inside a jitted function "
                        "happens once per compile, not per call"))
        return findings

    def _check_static_args(self, call: ast.Call, fn, lines,
                           relpath, findings) -> None:
        if fn is None:
            return
        params = [a.arg for a in fn.args.args]
        defaults = dict(zip(params[len(params)
                                   - len(fn.args.defaults):],
                            fn.args.defaults))
        marked: List[str] = []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        marked.append(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int) and \
                            el.value < len(params):
                        marked.append(params[el.value])
        for pname in marked:
            default = defaults.get(pname)
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                findings.append(Finding(
                    self.id, relpath, call.lineno, fn.name,
                    _src_line(lines, call.lineno),
                    f"static arg {pname!r} defaults to an unhashable "
                    f"{type(default).__name__.lower()} literal — "
                    f"static_argnums/static_argnames targets must be "
                    f"hashable by construction"))


# -- JIT-DEADLINE -----------------------------------------------------------


class DeadlineInJitRule(Rule):
    """Lifecycle control stays HOST-SIDE: no ``time.*`` deadline math
    inside a jit-wrapped step program.

    The request-lifecycle layer (serving/engine.py sweep) delivers
    cancellation, deadline expiry, and preemption at step boundaries
    by comparing host wall-clock against per-group deadlines.  Any
    ``time.*`` call inside a jitted function — not just the clocks
    JIT-PURITY flags, but ALL of the module (``time_ns``,
    ``monotonic_ns``, ``sleep``, ``strftime`` ...) — executes once at
    trace time and freezes into the compiled program: a deadline
    comparison there would evaluate exactly once and never fire
    again, silently turning "evict at the boundary" into "immortal".
    This is the Podracer decoupled-dataflow discipline
    (arXiv:2104.06272): scheduling decisions on the host, pure math
    on the device."""

    id = "JIT-DEADLINE"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        jitted_bodies, _ = _collect_jitted(tree)
        for body, label in jitted_bodies:
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if name.startswith("time."):
                    findings.append(Finding(
                        self.id, relpath, node.lineno, label,
                        _src_line(lines, node.lineno),
                        f"{name}() inside a jitted program: deadline/"
                        f"lifecycle math is host-side scheduling — "
                        f"it freezes at trace time in a compiled "
                        f"step, so a deadline check here would "
                        f"evaluate once and never fire again"))
        return findings


# -- HOST-SYNC --------------------------------------------------------------


_JAX_ROOTS = ("jax", "jnp", "jrandom")

_HOT_PATHS = ("serving/engine.py", "serving/slots.py")


def _is_jax_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    root = name.split(".", 1)[0]
    return root in _JAX_ROOTS and not name.endswith("device_get")


class HostSyncRule(Rule):
    """No implicit device->host syncs in the decode hot path.

    ``np.asarray``/``np.array``/``float``/``int`` applied directly to
    a jax-producing call, and ``.tolist()``/``.item()``, each hide a
    ``block_until_ready`` — the decode loop stalls on device work the
    author never sees.  The sanctioned spelling is explicit:
    ``np.asarray(jax.device_get(x))``.  Scoped to the engine step /
    decode modules (serving/engine.py, serving/slots.py) where one
    stray sync costs every resident stream a step."""

    id = "HOST-SYNC"

    def applies_to(self, relpath: str) -> bool:
        return any(relpath.endswith(p) for p in _HOT_PATHS)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if name in ("np.asarray", "np.array", "float",
                            "int") and node.args and \
                        _is_jax_call(node.args[0]):
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"{name}() directly on a jax call is an "
                        f"implicit device->host sync in the decode "
                        f"hot path; spell it jax.device_get(...) so "
                        f"the sync is visible"))
                elif tail in ("tolist", "item") and \
                        isinstance(node.func, ast.Attribute) and \
                        not node.args:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f".{tail}() in the decode hot path is an "
                        f"implicit device->host sync; device_get "
                        f"once, index on the host"))
                self.generic_visit(node)

        V().visit(tree)
        return findings


# -- EXC-SWALLOW ------------------------------------------------------------


class ExcSwallowRule(Rule):
    """``except Exception: pass`` — or ``continue`` — (body is only
    control flow) silently drops errors.  The ``continue`` form is
    the loop-sweep variant the request-lifecycle paths invite: an
    eviction/cancellation sweep that swallows per-item errors and
    moves on leaks the very slots it exists to reclaim, invisibly.
    Best-effort teardown belongs in the committed baseline with a
    justification; everything else must at least log at debug level
    so a broken subsystem is diagnosable."""

    id = "EXC-SWALLOW"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_ExceptHandler(self, node):
                if self._broad(node.type) and all(
                        isinstance(s, (ast.Pass, ast.Continue))
                        for s in node.body):
                    what = "continue" if any(
                        isinstance(s, ast.Continue)
                        for s in node.body) else "pass"
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"except-and-{what} drops the error without "
                        f"a trace; log it (debug level is enough) or "
                        f"baseline it as best-effort teardown"))
                self.generic_visit(node)

            @staticmethod
            def _broad(t) -> bool:
                if t is None:
                    return True
                names = [dotted_name(el) for el in t.elts] \
                    if isinstance(t, ast.Tuple) else [dotted_name(t)]
                return any(n in ("Exception", "BaseException")
                           for n in names)

        V().visit(tree)
        return findings


# -- PAGE-REF ---------------------------------------------------------------


_PAGE_POOL_MODULE = "serving/paged.py"
_PAGE_POOL_LOCK = re.compile(r"(^|_)page_lock$")
_PAGE_INTERNALS = {"refcounts", "_free_pages", "page_tables"}
_PAGE_MUTABLE = {"refcounts", "_free_pages"}
_LIST_MUTATORS = {"append", "pop", "remove", "extend", "insert",
                  "clear"}


class PageRefRule(Rule):
    """Paged-KV page-pool discipline (serving/paged.py).

    The page pool's accounting state — ``refcounts`` and the
    ``_free_pages`` list — is mutated from handler threads (prefix
    pin/unpin) AND the engine thread (admission reserve, eviction
    release), so every mutation must sit under the pool's
    ``_page_lock``; a lockless bump is a lost-update seed that frees
    a page still mapped into a co-tenant's table (the stale-KV leak
    class the page-poison tests pin).  And the pool's internals are
    PRIVATE to the pool module: outside it, code must go through the
    accounting API (``pin``/``unpin``/``try_reserve``/``can_admit``)
    — flagged are (a) inside the pool module, ``refcounts`` /
    ``_free_pages`` mutations not lexically under a ``with
    *page_lock`` block; (b) outside it, ANY access to ``refcounts`` /
    ``_free_pages`` / ``page_tables`` attributes; (c) outside it, raw
    integer page-index literals passed to ``pin``/``unpin`` — page
    ids are pool-issued handles, never constants."""

    id = "PAGE-REF"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        in_pool = relpath.replace("\\", "/").endswith(
            _PAGE_POOL_MODULE)
        parents: Dict[ast.AST, ast.AST] = {}
        for p in ast.walk(tree):
            for c in ast.iter_child_nodes(p):
                parents[c] = p

        def _tail_attr(node) -> Optional[str]:
            """The attribute name at the base of a target chain:
            ``self.refcounts[i]`` -> ``refcounts``."""
            while isinstance(node, ast.Subscript):
                node = node.value
            if isinstance(node, ast.Attribute):
                return node.attr
            return None

        def _locked(node) -> bool:
            """A ``with *page_lock`` ancestor BELOW the nearest
            enclosing function def — a with-block outside the def
            doesn't protect code that runs later."""
            n = parents.get(node)
            while n is not None:
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                    return False
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        name = dotted_name(item.context_expr) or ""
                        if _PAGE_POOL_LOCK.search(
                                name.rsplit(".", 1)[-1]):
                            return True
                n = parents.get(n)
            return False

        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def _flag(self, node, msg):
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno), msg))

            def _check_mutation(self, node, target):
                attr = _tail_attr(target)
                if attr in _PAGE_MUTABLE and not _locked(node):
                    self._flag(
                        node,
                        f"page-pool state ({attr}) mutated outside "
                        f"`with _page_lock`: handler threads and the "
                        f"engine thread race here — a lost update "
                        f"frees a page still mapped by a co-tenant")

            def visit_Assign(self, node):
                if in_pool:
                    for t in node.targets:
                        self._check_mutation(node, t)
                self.generic_visit(node)

            def visit_AnnAssign(self, node):
                if in_pool and node.value is not None:
                    self._check_mutation(node, node.target)
                self.generic_visit(node)

            def visit_AugAssign(self, node):
                if in_pool:
                    self._check_mutation(node, node.target)
                self.generic_visit(node)

            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if in_pool:
                    # free-list mutation via list methods
                    if tail in _LIST_MUTATORS and \
                            isinstance(node.func, ast.Attribute) and \
                            _tail_attr(node.func.value) in \
                            _PAGE_MUTABLE and not _locked(node):
                        self._flag(
                            node,
                            f"free-list .{tail}() outside `with "
                            f"_page_lock`: page allocation must be "
                            f"race-free")
                elif tail in ("pin", "unpin") and \
                        isinstance(node.func, ast.Attribute):
                    for arg in node.args:
                        for el in ast.walk(arg):
                            if isinstance(el, ast.Constant) and \
                                    isinstance(el.value, int) and \
                                    not isinstance(el.value, bool):
                                self._flag(
                                    node,
                                    f"raw page-index literal "
                                    f"{el.value} passed to "
                                    f".{tail}(): page ids are "
                                    f"pool-issued handles, never "
                                    f"constants")
                                break
                        else:
                            continue
                        break
                self.generic_visit(node)

            def visit_Attribute(self, node):
                if not in_pool and node.attr in _PAGE_INTERNALS:
                    self._flag(
                        node,
                        f"page-pool internal .{node.attr} accessed "
                        f"outside the pool module: use the "
                        f"accounting API (pin/unpin/try_reserve/"
                        f"can_admit/page_stats)")
                self.generic_visit(node)

        V().visit(tree)
        return findings


# Serving KV-pool state attrs whose allocation must flow through the
# mesh-aware allocator helpers (slots._alloc_stacked /
# paged._alloc_pool commit pools to their NamedShardings at birth).
_POOL_STATE_ATTRS = {"_stacked", "_draft_stacked", "_pool",
                     "_draft_pool"}
_ZEROS_FAMILY = {"zeros", "ones", "full", "empty", "zeros_like",
                 "ones_like", "full_like"}
_ALLOC_HELPERS = re.compile(r"(^|\.)(_alloc|_ensure)")


class ShardLeakRule(Rule):
    """Meshed-serving placement discipline (serving/meshed.py).

    A meshed engine's step programs compile with explicit in/out
    shardings over committed operands; a host-built array placed
    UNCOMMITTED (``jax.device_put(x)`` with no sharding) lands on the
    default device, and feeding it to a mesh-compiled program forces
    a transfer/reshard on every call — invisible steady-state tax
    that profiles as mystery step latency.  The sanctioned spellings
    are ``device_put(x, sharding)`` / ``ServingMesh.put_replicated``
    (committed), or keeping the array host-side and letting the
    program's explicit ``in_shardings`` place it.  Pool-state
    allocations (``self._stacked = jnp.zeros(...)``) must go through
    the ``_alloc*``/``_ensure*`` helpers for the same reason: a pool
    born unsharded silently demotes every subsequent step to
    replicated layout."""

    id = "SHARD-LEAK"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def _flag(self, node, msg):
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno), msg))

            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if tail == "device_put" and len(node.args) == 1 \
                        and not node.keywords:
                    self._flag(
                        node,
                        "single-argument device_put places the array "
                        "UNCOMMITTED on the default device; fed to a "
                        "mesh-compiled program that costs a transfer "
                        "per call — pass a NamedSharding (or "
                        "ServingMesh.put_replicated)")
                self.generic_visit(node)

            def visit_Assign(self, node):
                if not _ALLOC_HELPERS.search(self.func):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr in _POOL_STATE_ATTRS and \
                                self._allocates(node.value):
                            self._flag(
                                node,
                                f"KV-pool state ({t.attr}) allocated "
                                f"outside the _alloc*/_ensure* "
                                f"helpers: pools must be committed "
                                f"to their mesh shardings at birth "
                                f"(an unsharded pool demotes every "
                                f"step to replicated layout)")
                self.generic_visit(node)

            @staticmethod
            def _allocates(value) -> bool:
                for n in ast.walk(value):
                    if isinstance(n, ast.Call):
                        name = dotted_name(n.func) or ""
                        if name.rsplit(".", 1)[-1] in _ZEROS_FAMILY:
                            return True
                return False

        V().visit(tree)
        return findings


# -- TIME-TRUTH -------------------------------------------------------------


_CLOCK_CALLS = {"time.perf_counter", "time.time"}
# The sanctioned device-sync spellings: any of these on a line
# between the clock read and the delta makes the delta honest.
_SYNC_TAILS = {"block_until_ready", "device_get"}


class TimeTruthRule(Rule):
    """Host-clock deltas must not time ASYNC jax dispatch.

    ``jax`` dispatch is asynchronous: a jitted call returns device
    futures, so ``t0 = time.perf_counter(); fn(...); dt =
    perf_counter() - t0`` measures how fast the HOST enqueued work,
    not how long the device ran — the number silently shrinks as
    programs grow (more async tail) and every consumer downstream
    (bench rows, step_device_share, SLO math) inherits the lie.
    Flagged: a ``<name> - t0``-style delta whose anchor is a
    ``time.perf_counter()``/``time.time()`` assignment in the same
    function, with at least one jax-rooted call (``jax.*`` /
    ``jnp.*`` / ``jrandom.*``, profiler markers excluded) on the
    lines between anchor and delta and NO ``jax.block_until_ready``
    / ``jax.device_get`` sync in that span.  Scoped to serving/ and
    benchmarks/ — the layers whose timings feed dashboards and
    committed rows.  HTTP/thread timing (no jax call in the span)
    never matches."""

    id = "TIME-TRUTH"

    def applies_to(self, relpath: str) -> bool:
        rp = "/" + relpath.replace("\\", "/")
        return _in_serving(relpath) or "/benchmarks/" in rp

    @staticmethod
    def _call_lines(body: ast.AST):
        """(clock assigns, jax-call lines, sync lines) for one
        function body, NOT descending into nested defs/lambdas (their
        calls run on their own schedule, not between this function's
        clock reads)."""
        anchors: Dict[str, List[int]] = {}
        jax_lines: List[int] = []
        sync_lines: Set[int] = set()

        def scan(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name) \
                        and isinstance(child.value, ast.Call) \
                        and dotted_name(child.value.func) \
                        in _CLOCK_CALLS:
                    anchors.setdefault(child.targets[0].id,
                                       []).append(child.lineno)
                if isinstance(child, ast.Call):
                    name = dotted_name(child.func) or ""
                    tail = name.rsplit(".", 1)[-1]
                    root = name.split(".", 1)[0]
                    if tail in _SYNC_TAILS:
                        sync_lines.add(child.lineno)
                    elif root in ("jax", "jnp", "jrandom") \
                            and not name.startswith("jax.profiler"):
                        jax_lines.append(child.lineno)
                scan(child)

        scan(body)
        return anchors, jax_lines, sync_lines

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_FunctionDef(self, node):
                self._stack.append(node.name)
                anchors, jax_lines, sync_lines = \
                    rule._call_lines(node)
                if anchors:
                    for sub in self._own_nodes(node):
                        if isinstance(sub, ast.BinOp) \
                                and isinstance(sub.op, ast.Sub) \
                                and isinstance(sub.right, ast.Name) \
                                and sub.right.id in anchors:
                            self._check_delta(sub, anchors,
                                              jax_lines, sync_lines)
                self.generic_visit(node)
                self._stack.pop()

            @staticmethod
            def _own_nodes(fn):
                """Walk ``fn``'s body without descending into nested
                defs/lambdas — their deltas anchor (and get checked)
                in their own scope."""
                stack = list(ast.iter_child_nodes(fn))
                while stack:
                    n = stack.pop()
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                        continue
                    yield n
                    stack.extend(ast.iter_child_nodes(n))

            visit_AsyncFunctionDef = visit_FunctionDef

            def _check_delta(self, sub, anchors, jax_lines,
                             sync_lines):
                # Anchor = the nearest clock assignment ABOVE the
                # delta (re-assignment in a loop re-anchors).
                prior = [ln for ln in anchors[sub.right.id]
                         if ln < sub.lineno]
                if not prior:
                    return
                a = max(prior)
                span_jax = [ln for ln in jax_lines
                            if a < ln <= sub.lineno]
                span_sync = any(a < ln <= sub.lineno
                                for ln in sync_lines)
                if span_jax and not span_sync:
                    findings.append(Finding(
                        rule.id, relpath, sub.lineno, self.func,
                        _src_line(lines, sub.lineno),
                        f"host-clock delta over async jax dispatch "
                        f"(jax call at line {span_jax[0]}, no "
                        f"block_until_ready/device_get since the "
                        f"clock read at line {a}): the delta times "
                        f"the ENQUEUE, not the device — sync first, "
                        f"or use the flight recorder's trace "
                        f"attribution for device truth"))

        V().visit(tree)
        return findings


# -- SNAPSHOT-LOCK ----------------------------------------------------------


class SnapshotLockRule(Rule):
    """The ``/debug/state`` consistency contract (docs/DESIGN.md):
    code holding a snapshot-board ``*state_lock`` must never acquire
    the device lock — directly or by calling into a device-
    dispatching entry point.

    The introspection surface exists to answer "why is the engine
    making no progress" — which it cannot do if serving a snapshot
    can queue behind the very device call that is wedged.  Flags,
    inside a ``with <...state_lock>`` body (not descending into
    nested defs):

    - a nested ``with`` on (or blocking ``.acquire()`` of) a lock
      named ``device_lock`` / ``_lock`` — the server's device lock;
    - calls whose dotted tail is a device-dispatching serving entry
      point (``generate`` / ``prefill_prompt`` / ``submit`` /
      ``tick`` / ``_decode_step`` / ``_advance_prefill``);
    - any ``jax.*`` call — snapshot serialization is plain host-dict
      work by contract, so no jax call belongs under the board lock
      (``jax.device_get`` and friends all sync against in-flight
      device work).
    """

    id = "SNAPSHOT-LOCK"

    _DEVICE_ENTRY = frozenset({
        "generate", "prefill_prompt", "submit", "tick",
        "_decode_step", "_advance_prefill"})
    _DEVICE_LOCKS = frozenset({"device_lock", "_lock"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        def _lock_tail(expr) -> str:
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
            return (name or "").rsplit(".", 1)[-1]

        class V(_ScopedVisitor):
            def visit_With(self, node):
                if any(_lock_tail(item.context_expr)
                       .endswith("state_lock")
                       for item in node.items):
                    for stmt in node.body:
                        self._scan(stmt)
                self.generic_visit(node)

            visit_AsyncWith = visit_With

            def _flag(self, node, msg: str) -> None:
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno),
                    f"{msg} while holding the snapshot state lock: "
                    f"/debug/state must answer even when the device "
                    f"is wedged — build the snapshot at a step "
                    f"boundary and serve the published copy "
                    f"(docs/DESIGN.md SNAPSHOT-LOCK)"))

            def _scan(self, node) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return      # runs later, not under the lock
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if _lock_tail(item.context_expr) \
                                in rule._DEVICE_LOCKS:
                            self._flag(item.context_expr,
                                       "acquiring the device lock")
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    tail = name.rsplit(".", 1)[-1]
                    if tail == "acquire" and \
                            isinstance(node.func, ast.Attribute) and \
                            (dotted_name(node.func.value) or "") \
                            .rsplit(".", 1)[-1] in rule._DEVICE_LOCKS:
                        self._flag(node,
                                   "acquiring the device lock")
                    elif tail in rule._DEVICE_ENTRY and \
                            isinstance(node.func, ast.Attribute):
                        self._flag(
                            node,
                            f"calling the device-dispatching entry "
                            f"point .{tail}()")
                    elif name.startswith("jax."):
                        self._flag(node, f"jax call ({name})")
                for child in ast.iter_child_nodes(node):
                    self._scan(child)

        V().visit(tree)
        return findings


# -- RETRY-BACKOFF ----------------------------------------------------------


class RetryBackoffRule(Rule):
    """Bounded-retry discipline in serving/ (docs/SERVING.md "Fault
    tolerance"): an unbounded ``while True`` retry loop around a jax
    or socket call — a broad handler that swallows the error and
    loops again — turns a PERMANENT failure (a dead device, a gone
    peer) into an invisible infinite spin: no error surfaces, no
    counter advances, and the caller hangs forever, which is exactly
    the crash-never anti-pattern the crash-only contract forbids.
    The sanctioned spelling is the shared
    :class:`~polyaxon_tpu.serving.recovery.RetryPolicy`: an attempt
    bound (``max_attempts``) plus jittered backoff (``delay_s``),
    escalating — raising, shedding, or quarantining — once retries
    exhaust.

    Flags, in serving/ only: a constant-true ``while`` loop whose
    body has a ``try`` around a ``jax.*`` or socket/HTTP I/O call
    with a broad handler (bare / ``Exception`` / ``BaseException`` /
    ``OSError`` family) that reaches the next iteration with NO
    bounded escape — no ``raise`` / ``return`` / ``break`` anywhere
    in the handler — while the loop nowhere references the bounded-
    retry spelling (``retry_policy`` / ``max_attempts`` /
    ``delay_s``).  Service loops with external termination
    (``while not self._stop``) are not constant-true and never
    flagged."""

    id = "RETRY-BACKOFF"

    _BROAD = frozenset({"Exception", "BaseException", "OSError",
                        "IOError", "ConnectionError", "TimeoutError",
                        "socket.error", "socket.timeout"})
    _BOUNDED = frozenset({"retry_policy", "max_attempts", "delay_s"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        def _walk_no_defs(node):
            """The loop-iteration view: nested defs/lambdas run on
            their own schedule, so nothing inside them retries (or
            bounds) THIS loop."""
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                yield from _walk_no_defs(child)

        def _risky_call(try_node) -> Optional[str]:
            for stmt in try_node.body:
                for n in _walk_no_defs(stmt):
                    if not isinstance(n, ast.Call):
                        continue
                    name = dotted_name(n.func) or ""
                    if name.startswith("jax."):
                        return name
                    if name.rsplit(".", 1)[-1] in _SOCKET_IO:
                        return name or "socket I/O"
            return None

        def _broad(t) -> bool:
            if t is None:
                return True
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            return any((dotted_name(el) or "") in rule._BROAD
                       for el in elts)

        def _escapes(handler) -> bool:
            return any(isinstance(n, (ast.Raise, ast.Return,
                                      ast.Break))
                       for n in _walk_no_defs(handler))

        def _bounded(loop) -> bool:
            for n in _walk_no_defs(loop):
                if isinstance(n, ast.Attribute) \
                        and n.attr in rule._BOUNDED:
                    return True
                if isinstance(n, ast.Name) \
                        and n.id in rule._BOUNDED:
                    return True
            return False

        class V(_ScopedVisitor):
            def visit_While(self, node):
                if isinstance(node.test, ast.Constant) \
                        and bool(node.test.value) \
                        and not _bounded(node):
                    for n in _walk_no_defs(node):
                        if isinstance(n, ast.Try):
                            self._check_try(n)
                self.generic_visit(node)

            def _check_try(self, t) -> None:
                risky = _risky_call(t)
                if risky is None:
                    return
                for h in t.handlers:
                    if _broad(h.type) and not _escapes(h):
                        findings.append(Finding(
                            rule.id, relpath, h.lineno, self.func,
                            _src_line(lines, h.lineno),
                            f"unbounded while-True retry around "
                            f"{risky}: a permanent failure spins "
                            f"forever with no error surfaced — "
                            f"bound it with the shared RetryPolicy "
                            f"(attempt < max_attempts + delay_s "
                            f"backoff; serving/recovery.py) and "
                            f"escalate once retries exhaust"))
                        return

        V().visit(tree)
        return findings


# -- TIER-XFER --------------------------------------------------------------


# Identifier shapes that name page-pool payload state: the pools
# themselves (_pool/_draft_pool/pool), page-id collections
# (pages/page_tables/shared_pages), and page-granular leaves.
_TIER_NAMES = re.compile(
    r"(^|_)(pages?|pools?)($|_)|page_table")

# The sanctioned tiered-memory helpers (serving/paged.py): the ONLY
# functions allowed to move page-pool payloads across the
# device<->host boundary.  Matched against the innermost enclosing
# function name.
_TIER_SANCTIONED = {"spill_pages", "rematerialize", "materialize",
                    "_alloc_pool", "scatter_cache"}


class TierXferRule(Rule):
    """Tiered-KV transfer discipline (serving/paged.py host tier).

    The two-tier prefix store moves page payloads device->host only
    through ``spill_pages`` (page-pressure reclaim) and host->device
    only through ``rematerialize``/``scatter_cache`` (prefix-hit
    admission / promotion) — both OFF the decode step path.  A stray
    ``jax.device_put``/``jax.device_get`` whose operand touches
    pool/page state is a page-sized PCIe transfer on whatever path it
    sits; on the step path it is a silent TTFT cliff (and on a mesh,
    an uncommitted placement on top — see SHARD-LEAK).  Flagged in
    serving/ outside the sanctioned helper set."""

    id = "TIER-XFER"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    @staticmethod
    def _touches_pool(node: ast.AST) -> Optional[str]:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) \
                    and _TIER_NAMES.search(n.attr):
                return n.attr
            if isinstance(n, ast.Name) \
                    and _TIER_NAMES.search(n.id):
                return n.id
        return None

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if tail in ("device_put", "device_get"):
                    inner = self._stack[-1] if self._stack else ""
                    if inner not in _TIER_SANCTIONED:
                        for arg in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            hit = rule._touches_pool(arg)
                            if hit:
                                findings.append(Finding(
                                    rule.id, relpath, node.lineno,
                                    self.func,
                                    _src_line(lines, node.lineno),
                                    f"{tail} of page-pool payload "
                                    f"({hit}) outside the sanctioned "
                                    f"tiered-memory helpers "
                                    f"({', '.join(sorted(_TIER_SANCTIONED))})"
                                    f": page-sized device<->host "
                                    f"transfers belong to the spill/"
                                    f"re-materialize tier — on the "
                                    f"step path this is a silent "
                                    f"TTFT cliff"))
                                break
                self.generic_visit(node)

        V().visit(tree)
        return findings


# -- SOCKET-TIMEOUT ---------------------------------------------------------


class SocketTimeoutRule(Rule):
    """Explicit timeouts on every outbound network call in serving/.

    The router tier probes replicas and forwards requests over plain
    sockets; a ``socket.create_connection`` / ``urllib.request.
    urlopen`` / ``http.client.HTTPConnection`` call WITHOUT an
    explicit timeout inherits the global default (None = block
    forever) — and a timeout-less probe against a hung replica is
    how the whole ROUTER wedges: one dead endpoint collects the
    probe thread, then the handler threads, and the healthy fleet
    behind the router goes dark with it (the arXiv:2011.03641
    pathology moved up a tier).  Every outbound call must pass
    ``timeout=`` (or the positional timeout its signature defines).

    Flagged call shapes (by trailing name): ``create_connection``
    (timeout is the 2nd positional), ``urlopen`` (3rd), and the
    ``HTTPConnection``/``HTTPSConnection`` constructors (kwarg).  A
    visible timeout — positional in the right slot or ``timeout=``
    anywhere — clears the finding; reading the VALUE is out of scope
    (a named constant is fine, and ``timeout=None`` spelled out at
    least shows intent at the call site)."""

    id = "SOCKET-TIMEOUT"

    # tail -> minimum positional-arg count that covers the timeout
    # slot (0 = keyword-only for this shape).
    _SHAPES = {"create_connection": 2, "urlopen": 3,
               "HTTPConnection": 0, "HTTPSConnection": 0}

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                pos_slot = rule._SHAPES.get(tail)
                if pos_slot is not None:
                    has_kw = any(kw.arg == "timeout"
                                 for kw in node.keywords)
                    has_pos = pos_slot > 0 \
                        and len(node.args) >= pos_slot
                    if not has_kw and not has_pos:
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            f"{tail} without an explicit timeout: "
                            f"the default blocks forever, and a "
                            f"timeout-less probe/forward against a "
                            f"hung replica wedges the router (and "
                            f"every healthy replica behind it) — "
                            f"pass timeout= at the call site"))
                self.generic_visit(node)

        V().visit(tree)
        return findings


# -- WIRE-VERIFY ------------------------------------------------------------


class WireVerifyRule(Rule):
    """Checksum discipline on wire-payload admission (serving/
    paged.py fleet wire format).

    Every payload that crosses the fleet wire — a ``/prefix/fetch``
    response, a handoff push, a disagg KV admission — is a
    length-prefixed header plus raw C-order buffers, and the header
    carries a crc32 over the buffer body.  The ONLY safe way to
    admit one is ``unpack_spilled``, which verifies that checksum
    and raises the typed ``WirePayloadError`` on mismatch (HTTP 400
    ``payload_integrity``, degrade-to-re-prefill).  A hand-rolled
    decode — ``np.frombuffer`` over wire bytes in a function that
    neither calls ``crc32`` itself nor goes through
    ``unpack_spilled`` — admits whatever a truncated proxy response
    or a torn socket handed it, and the corruption surfaces later as
    silently wrong KV (wrong tokens, not an error).  Flagged in
    serving/: any ``frombuffer`` call whose enclosing function
    contains neither a ``crc32`` call nor an ``unpack_spilled``
    call."""

    id = "WIRE-VERIFY"

    _VERIFIERS = frozenset({"crc32", "unpack_spilled"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self
        # Calls grouped by INNERMOST enclosing def.  The
        # verification scope is the LEXICAL chain: a closure decodes
        # under its enclosing function's crc32 (one body, one
        # payload), but a sibling top-level helper does not — it can
        # be called from anywhere, so a crc32 in one caller blesses
        # nothing.
        scopes: Dict[Tuple[str, ...], Dict[str, Any]] = {}

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                key = tuple(self._stack)
                sc = scopes.setdefault(
                    key, {"func": self.func, "tails": set(),
                          "hits": []})
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                sc["tails"].add(tail)
                if tail == "frombuffer":
                    sc["hits"].append(node)
                self.generic_visit(node)

        V().visit(tree)
        for key, sc in scopes.items():
            if not sc["hits"]:
                continue
            chain_tails = set()
            for k in range(len(key) + 1):
                outer = scopes.get(key[:k])
                if outer is not None:
                    chain_tails |= outer["tails"]
            if rule._VERIFIERS & chain_tails:
                continue
            for node in sc["hits"]:
                findings.append(Finding(
                    rule.id, relpath, node.lineno, sc["func"],
                    _src_line(lines, node.lineno),
                    "frombuffer over wire payload without a "
                    "checksum verify in the same function: admit "
                    "fleet-wire bytes through unpack_spilled (or "
                    "verify crc32 here) — an unverified decode "
                    "turns a truncated/torn transfer into silently "
                    "wrong KV instead of the typed "
                    "payload_integrity degrade"))
        return findings


# -- PHASE-ENUM -------------------------------------------------------------


class PhaseEnumRule(Rule):
    """Closed phase vocabulary for the tail-latency ledger
    (serving/forensics.py).

    The phase ledger's whole value is that every surface — history
    record, ``timings`` block, stitched fleet timeline, /metrics
    gauges, the anomaly sentry — speaks ONE enum: the ``PHASE_*``
    constants in forensics.py.  A consumer that hand-writes
    ``"queue_wait"`` instead of importing ``PHASE_QUEUE_WAIT``
    compiles today and silently stops matching the day the enum is
    renamed or extended — dashboards join on a name that no longer
    exists, and nothing errors.  Flagged in serving/ outside
    forensics.py: any string literal spelling a phase-enum member.

    Deliberately narrow: only the phase names UNIQUE to the ledger
    vocabulary are flagged — ``prefill``/``decode``/``kv_handoff``/
    ``prefill_remote`` double as span names all over the stack and
    cannot be flagged without drowning the signal.  The test suite
    pins this rule's set against the live enum (tests/
    test_analysis.py), so a new phase constant that is not also a
    span name must be added here or the suite fails."""

    id = "PHASE-ENUM"

    # PHASES + ROUTER_PHASES minus the names shared with the span
    # vocabulary (prefill, decode, kv_handoff, prefill_remote).
    _PHASE_LITERALS = frozenset({
        "queue_wait", "device_lock_wait", "admit_wait",
        "kv_wire_fetch", "preempt_gap", "finalize", "unattributed",
        "route_pick", "replica_attempt", "retry_backoff",
    })

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath) \
            and not relpath.endswith("forensics.py")

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Constant(self, node):
                if isinstance(node.value, str) \
                        and node.value in rule._PHASE_LITERALS:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"phase name {node.value!r} written as a "
                        f"string literal: import the PHASE_* "
                        f"constant from serving/forensics.py — a "
                        f"hand-spelled phase silently stops "
                        f"matching when the enum changes (the "
                        f"ledger partition is only auditable "
                        f"because every surface speaks ONE "
                        f"vocabulary)"))
                self.generic_visit(node)

        V().visit(tree)
        return findings


ALL_RULES: Tuple[Rule, ...] = (RngDetRule(), LockHoldRule(),
                               JitPurityRule(), DeadlineInJitRule(),
                               HostSyncRule(), ExcSwallowRule(),
                               PageRefRule(), ShardLeakRule(),
                               TimeTruthRule(), SnapshotLockRule(),
                               RetryBackoffRule(), TierXferRule(),
                               SocketTimeoutRule(),
                               WireVerifyRule(), PhaseEnumRule())
RULE_IDS: Tuple[str, ...] = tuple(r.id for r in ALL_RULES)
