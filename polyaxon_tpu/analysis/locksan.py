"""Lock-order sanitizer for the serving locks.

Static analysis (rules.LockHoldRule) can see a blocking call inside a
``with device_lock`` body; it cannot see two threads acquiring the
same pair of locks in opposite orders, or a lock held across a slow
device call — both only exist at runtime.  This module wraps the
serving locks in recording proxies:

- every ``acquire`` records the edge (each currently-held lock ->
  newly-acquired lock) in a process-wide acquisition graph keyed by
  lock NAME; acquiring an edge whose reverse has been observed raises
  :class:`LockOrderError` at the acquisition site — the classic
  deadlock is reported deterministically on the FIRST inverted
  acquisition, whether or not the schedule would actually have
  deadlocked this run.  Re-acquiring a lock the same thread already
  holds (threading.Lock self-deadlock) raises too.
- ``release`` checks the hold duration against the sanitizer's
  per-name limits (e.g. ``device_lock`` held longer than a step
  budget).  Violations are recorded in ``sanitizer.violations``
  always, and raised at release when ``raise_on_violation`` — unless
  an exception is already propagating out of the ``with`` block
  (never mask the original error).

Overhead is a dict lookup + list append per acquire under a small
internal mutex — fine for tests and the opt-in ``ptpu serve
--sanitize`` flag, not meant for benchmark runs (the bench keeps it
off by default and says so: benchmarks/bench_serving_load.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["LockOrderError", "LockHeldTooLongError", "LockSanitizer",
           "SanitizedLock", "LOCK_REGISTRY", "RECEIVER_TYPES"]


# -- the serving lock registry ----------------------------------------
#
# The static analyzer (analysis/lockgraph.py) names a lock by its
# declaring class (``Telemetry._lock`` and ``Replica._lock`` are
# different locks); the runtime sanitizer names a lock by the string
# passed to :meth:`LockSanitizer.wrap`.  This registry is the single
# place the two vocabularies meet: static ``Class.attr`` identities
# that alias the same underlying lock map to one canonical name — the
# wrap name for sanitized locks, so the static graph's edges are
# directly comparable with ``stats()["edges"]``.
#
# The one genuine alias today: ModelServer passes its ``_lock`` into
# DecodeEngine as ``device_lock`` (engine.py takes ``device_lock or
# threading.Lock()``), so acquisitions through either attribute are
# the SAME lock and must share a node or the inversion
# device_lock -> X -> ModelServer._lock would be invisible statically.
LOCK_REGISTRY: Dict[str, str] = {
    "ModelServer._lock": "device_lock",
    "DecodeEngine.device_lock": "device_lock",
    "ModelServer._stats_lock": "_stats_lock",
    "ModelServer._prefix_lock": "_prefix_lock",
}

# Receiver-name conventions the static analyzer uses to type a
# non-``self`` receiver it cannot infer from assignments — e.g. the
# HTTP handler closure's ``ms._stats_lock`` and the legacy
# coalescer's ``self.ms._lock``.  Conventions, not inference: keep the
# list short and only for names used consistently across serving/.
RECEIVER_TYPES: Dict[str, str] = {
    "ms": "ModelServer",
    "sentry": "AnomalySentry",
    "engine": "DecodeEngine",
}


class LockOrderError(RuntimeError):
    """Two locks were acquired in an order whose reverse has also
    been observed — a deadlock waiting for the right schedule."""


class LockHeldTooLongError(RuntimeError):
    """A sanitized lock was held past its configured limit."""


class SanitizedLock:
    """Drop-in ``threading.Lock`` proxy that reports acquire/release
    to its :class:`LockSanitizer` (context manager, ``acquire`` with
    blocking/timeout, ``release``, ``locked``)."""

    def __init__(self, name: str, sanitizer: "LockSanitizer",
                 lock: Optional[threading.Lock] = None):
        self.name = name
        self.san = sanitizer
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        self.san._pre_acquire(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self.san._post_acquire(self.name)
        return got

    def release(self) -> None:
        err = self.san._pre_release(self.name)
        self._lock.release()
        if err is not None and self.san.raise_on_violation:
            raise err

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        err = self.san._pre_release(self.name)
        self._lock.release()
        if err is not None and self.san.raise_on_violation \
                and exc_type is None:
            # Never mask an in-flight exception with the sanitizer's.
            raise err

    def __repr__(self) -> str:
        return f"SanitizedLock({self.name!r}, {self._lock!r})"


class LockSanitizer:
    """Process-wide acquisition-graph recorder shared by a set of
    :class:`SanitizedLock` proxies.

    ``max_hold_s`` maps lock NAME -> maximum seconds it may be held
    (omit a name to leave it unbounded; ``device_lock`` is the
    intended customer — a hold longer than one step budget means some
    caller is doing whole-request work under the step lock).
    ``violations`` accumulates (kind, message) tuples whether or not
    ``raise_on_violation`` is set; with ``raise_on_violation=False``
    inversions and long holds are record-only (a server exposing the
    sanitizer in /info reports without crashing traffic).  The one
    exception is same-thread re-acquisition, which raises regardless:
    letting the acquire proceed would REALLY deadlock the thread."""

    def __init__(self, max_hold_s: Optional[Dict[str, float]] = None,
                 raise_on_violation: bool = True):
        self.max_hold_s = dict(max_hold_s or {})
        self.raise_on_violation = bool(raise_on_violation)
        self._mutex = threading.Lock()
        # (held_name, acquired_name) -> True; edges are by NAME, so
        # the graph is tiny and inversion detection is one dict probe
        self._edges: Dict[Tuple[str, str], bool] = {}
        self._tls = threading.local()
        self.violations: List[Tuple[str, str]] = []
        self.acquisitions = 0

    # -- proxy construction --------------------------------------------

    def wrap(self, name: str,
             lock: Optional[threading.Lock] = None) -> SanitizedLock:
        """A sanitized proxy for ``lock`` (or a fresh Lock) under
        ``name`` — names are the graph's nodes, so wrap each distinct
        lock with a distinct name."""
        return SanitizedLock(name, self, lock)

    # -- recording ------------------------------------------------------

    def _held(self) -> List[Tuple[str, float]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _pre_acquire(self, name: str) -> None:
        held = self._held()
        if any(h == name for h, _ in held):
            # Always raised, even in record-only mode: proceeding
            # would REALLY deadlock this thread on the non-reentrant
            # lock — there is no "observe and continue" option.
            self._note("self-deadlock",
                       f"thread already holds {name!r} and is "
                       f"acquiring it again (threading.Lock is not "
                       f"reentrant)")
            raise LockOrderError(
                f"re-acquiring {name!r} on the same thread")
        inverted = None
        with self._mutex:
            for h, _ in held:
                self._edges[(h, name)] = True
                if (name, h) in self._edges:
                    inverted = h
        if inverted is not None:
            msg = (f"lock-order inversion: this thread holds "
                   f"{inverted!r} while acquiring {name!r}, but the "
                   f"order {name!r} -> {inverted!r} has also been "
                   f"observed — a deadlock under the right schedule")
            self._note("inversion", msg)
            if self.raise_on_violation:
                raise LockOrderError(msg)

    def _post_acquire(self, name: str) -> None:
        self._held().append((name, time.perf_counter()))
        with self._mutex:
            self.acquisitions += 1

    def _pre_release(self, name: str
                     ) -> Optional[LockHeldTooLongError]:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _, t0 = held.pop(i)
                break
        else:
            return None       # released by a thread that never
            #                   acquired through the proxy (foreign
            #                   handoff) — nothing to time
        limit = self.max_hold_s.get(name)
        if limit is not None:
            dt = time.perf_counter() - t0
            if dt > limit:
                msg = (f"{name!r} held {dt:.3f}s (limit {limit}s): "
                       f"whole-request work is running under a "
                       f"step-granularity lock")
                self._note("long-hold", msg)
                return LockHeldTooLongError(msg)
        return None

    def _note(self, kind: str, msg: str) -> None:
        with self._mutex:
            self.violations.append((kind, msg))

    # -- introspection --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._mutex:
            return {
                "acquisitions": self.acquisitions,
                "edges": sorted(f"{a}->{b}"
                                for a, b in self._edges),
                "violations": [list(v) for v in self.violations],
            }
