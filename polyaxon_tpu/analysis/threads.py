"""THREAD-SHARE: cross-thread shared-state analysis.

Rides the whole-program model from :mod:`analysis.lockgraph` (call
graph, per-site held-lock sets, attribute writes) and answers the
question the lock graph doesn't: *which attributes are written by two
threads that agree on no lock?*

1. **Thread roots** are inferred, not configured: every
   ``Thread(target=f)`` site, every ``run`` method of a
   ``threading.Thread`` subclass, every HTTP handler entry point
   (``do_GET``/``do_POST``/...), and every ``Timer(t, f)`` callback.
   The engine loop shows up via its ``Thread(target=self._loop)``
   spawn like everything else.  The main thread (public API calls —
   ``close()``, constructor-time wiring) is deliberately NOT a root:
   it would make every attribute bi-rooted and drown the signal; the
   contract this family checks is between the *standing* threads.

2. Per root, a **must-held** set is propagated through the call graph
   (meet = intersection over call paths, seeded empty at the root):
   the locks a function is guaranteed to hold whenever that thread
   reaches it.  A write site's effective protection is the must-held
   set plus whatever is lexically held at the write.

3. A finding is one (class, attribute) pair written from ≥ 2 roots
   whose effective lock sets have **empty intersection** — no single
   lock orders those writes.  Constructor writes (``__init__`` et
   al.) are construction-time publication and don't count.

Sanctioned lock-free sharing is annotated in the code, not silenced
in config: ``# ptpu: lockfree[reason]`` on (or directly above) any
write to the attribute sanctions the whole attribute — the idiom for
GIL-atomic monotonic counters, epoch stamps read for staleness only,
and single-writer/racy-reader gauges.  The usual machinery still
applies on top: ``# ptpu: ignore[THREAD-SHARE]`` per line, and the
committed baseline for historical findings.

Precision limits are the model's (see lockgraph.py docstring): a call
the model cannot resolve contributes no reachability, so a write
reached only through an untyped receiver is invisible — the locksan
runtime cross-check exists precisely to keep the model honest on the
paths tests exercise.
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .rules._base import Finding, _src_line
from .lockgraph import (ProgramModel, build_model, _CTOR_NAMES,
                        WriteSite)

__all__ = ["thread_roots", "thread_share_findings", "analyze"]

_LOCKFREE = re.compile(r"#\s*ptpu:\s*lockfree\[([^\]]*)\]")

_HANDLER_ENTRIES = ("do_GET", "do_POST", "do_PUT", "do_DELETE",
                    "do_HEAD", "do_PATCH")


def thread_roots(model: ProgramModel) -> Dict[str, str]:
    """fqn -> display name for every inferred thread entry point."""
    roots: Dict[str, str] = {}
    # Thread(target=...) and Timer(t, fn) spawn sites.
    for fi in model.functions.values():
        for sp in fi.spawns:
            if sp.target_fqn and sp.target_fqn in model.functions:
                tgt = model.functions[sp.target_fqn]
                label = sp.thread_name or "thread"
                roots.setdefault(sp.target_fqn, f"{label}@{tgt.qual}")
    # threading.Thread subclasses: run() is the entry point.
    for cls in model.classes.values():
        if "Thread" in cls.bases and "run" in cls.methods:
            fqn = cls.methods["run"]
            roots.setdefault(fqn, f"thread@{cls.name}.run")
    # HTTP handler pool entry points.
    for fqn, fi in model.functions.items():
        if fi.cls is not None and fi.name in _HANDLER_ENTRIES:
            roots.setdefault(fqn, f"handler@{fi.qual}")
    return roots


def _per_connection_classes(model: ProgramModel) -> Set[str]:
    """HTTP handler classes: http.server constructs a fresh instance
    per connection, so their ``self`` attributes are thread-private
    and never shared-state findings."""
    out: Set[str] = set()
    for cls in model.classes.values():
        if ("BaseHTTPRequestHandler" in cls.bases
                or any(m in cls.methods for m in _HANDLER_ENTRIES)):
            out.add(cls.name)
    return out


def _must_held(model: ProgramModel,
               root: str) -> Dict[str, FrozenSet[str]]:
    """Locks guaranteed held whenever this root's thread reaches each
    function (meet-over-call-paths, intersection)."""
    held: Dict[str, FrozenSet[str]] = {root: frozenset()}
    work: List[str] = [root]
    while work:
        f = work.pop()
        fi = model.functions.get(f)
        if fi is None:
            continue
        base = held[f]
        for cs in fi.calls:
            contrib = base | frozenset(cs.held)
            for t in cs.targets:
                if t not in model.functions:
                    continue
                cur = held.get(t)
                new = contrib if cur is None else (cur & contrib)
                if cur is None or new != cur:
                    held[t] = new
                    work.append(t)
    return held


def _lockfree_reason(model: ProgramModel, w: WriteSite,
                     def_line: int) -> Optional[str]:
    """``# ptpu: lockfree[reason]`` on the write line, the line
    directly above, or on/above the enclosing ``def`` line (which
    sanctions every write in that function — for the
    reset-a-batch-of-fields idiom where one ownership argument
    covers them all)."""
    lines = model.sources.get(w.relpath, ())
    for ln in (w.line, w.line - 1, def_line, def_line - 1):
        m = _LOCKFREE.search(_src_line(lines, ln))
        if m:
            return m.group(1)
    return None


def thread_share_findings(model: ProgramModel) -> List[Finding]:
    roots = thread_roots(model)
    if len(roots) < 2:
        return []
    # (class, attr) -> root fqn -> [(write, effective held)]
    shared: Dict[Tuple[str, str],
                 Dict[str, List[Tuple[WriteSite, FrozenSet[str]]]]] = {}
    sanctioned: Set[Tuple[str, str]] = set()
    private = _per_connection_classes(model)
    for root in roots:
        held = _must_held(model, root)
        for fqn in held:
            fi = model.functions[fqn]
            if fi.name in _CTOR_NAMES:
                continue            # construction-time publication
            def_line = getattr(fi.node, "lineno", 0)
            for w in fi.writes:
                if w.cls in private:
                    continue        # per-connection instance
                if _lockfree_reason(model, w, def_line) is not None:
                    sanctioned.add((w.cls, w.attr))
                    continue
                eff = held[fqn] | frozenset(w.held)
                shared.setdefault((w.cls, w.attr), {}).setdefault(
                    root, []).append((w, eff))
    out: List[Finding] = []
    for (cls, attr), by_root in sorted(shared.items()):
        if (cls, attr) in sanctioned or len(by_root) < 2:
            continue
        common: Optional[FrozenSet[str]] = None
        sites: List[Tuple[WriteSite, FrozenSet[str]]] = []
        for writes in by_root.values():
            for w, eff in writes:
                common = eff if common is None else (common & eff)
                sites.append((w, eff))
        if common:
            continue                # one lock orders every write
        sites.sort(key=lambda p: (p[0].relpath, p[0].line))
        anchor = min(
            sites, key=lambda p: (bool(p[1]), p[0].relpath, p[0].line)
        )[0]
        root_names = ", ".join(sorted(roots[r] for r in by_root))
        examples = "; ".join(
            f"{w.relpath}:{w.line} [{w.func}] holds "
            f"{{{', '.join(sorted(eff)) or 'nothing'}}}"
            for w, eff in sites[:3])
        more = f" (+{len(sites) - 3} more)" if len(sites) > 3 else ""
        out.append(Finding(
            rule="THREAD-SHARE", path=anchor.relpath, line=anchor.line,
            func=anchor.func,
            code=_src_line(model.sources.get(anchor.relpath, ()),
                           anchor.line),
            message=(f"{cls}.{attr} is written from "
                     f"{len(by_root)} thread roots ({root_names}) "
                     f"with no common lock: {examples}{more} — guard "
                     f"the writes with one lock or annotate one with "
                     f"'# ptpu: lockfree[reason]' if the sharing is "
                     f"by design")))
    out.sort(key=lambda f: f.sort_key())
    return out


def analyze(sources: Dict[str, str]) -> List[Finding]:
    """THREAD-SHARE program analysis over the in-scope file set."""
    return thread_share_findings(build_model(sources))
