"""Committed-findings baseline for ``ptpu check``.

The baseline is the explicit, reviewed list of findings the repo has
decided to live with — each entry carries a human justification, so
"we checked and it's fine" is a diffable artifact instead of tribal
knowledge.  Entries match findings on (rule, path, enclosing
function, source-line text) with a count, NOT on line numbers:
editing code above a baselined site doesn't invalidate it, while
changing the flagged line itself (or adding a second occurrence)
surfaces as a NEW finding — exactly the review granularity wanted.

``ptpu check`` exits non-zero on findings beyond the baseline;
``--update-baseline`` rewrites the file (stable sort, justifications
preserved for entries that survive; new entries get a TODO
placeholder a reviewer must replace).
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Sequence, Tuple

from .rules import Finding

__all__ = ["DEFAULT_BASELINE", "load_baseline", "save_baseline",
           "apply_baseline"]

# The committed baseline ships inside the package so `ptpu check`
# finds it from any working directory.
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")

_Key = Tuple[str, str, str, str]

_TODO = "TODO: justify or fix (written by --update-baseline)"


def _entry_key(e: Dict) -> _Key:
    return (e["rule"], e["path"], e.get("func", "<module>"),
            e["code"])


def load_baseline(path: str) -> List[Dict]:
    """Entries from a baseline file; a missing file is an empty
    baseline (first run of a fresh checkout)."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", []) if isinstance(doc, dict) else doc
    for e in entries:
        for field in ("rule", "path", "code"):
            if field not in e:
                raise ValueError(
                    f"{path}: baseline entry missing {field!r}: {e}")
        e.setdefault("func", "<module>")
        e.setdefault("count", 1)
        e.setdefault("justification", _TODO)
    return entries


def save_baseline(path: str, findings: Sequence[Finding],
                  previous: Sequence[Dict] = (),
                  preserve: Sequence[Dict] = ()) -> List[Dict]:
    """Write ``findings`` as the new baseline, carrying forward
    justifications from ``previous`` where the entry survives.
    ``preserve`` entries are kept VERBATIM — the CLI passes the
    previous entries for paths OUTSIDE the checked set, so updating
    from a path subset can never delete (and lose the written
    justifications of) debt it didn't re-examine.  Entries are sorted
    by (path, func, rule, code) so baseline diffs are reviewable."""
    kept = {_entry_key(e): e.get("justification", _TODO)
            for e in previous}
    counts = Counter(f.key() for f in findings)
    lineno = {}
    for f in findings:
        lineno.setdefault(f.key(), f.line)
    entries = [
        {"rule": rule, "path": p, "func": func, "code": code,
         "count": n, "line": lineno[(rule, p, func, code)],
         "justification": kept.get((rule, p, func, code), _TODO)}
        for (rule, p, func, code), n in counts.items()]
    built = {_entry_key(e) for e in entries}
    entries += [dict(e) for e in preserve
                if _entry_key(e) not in built]
    entries.sort(key=lambda e: (e["path"], e["func"], e["rule"],
                                e["code"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1)
        f.write("\n")
    return entries


def apply_baseline(findings: Sequence[Finding],
                   entries: Sequence[Dict]
                   ) -> Tuple[List[Finding], List[Dict]]:
    """Split findings against the baseline.

    Returns ``(new, stale)``: ``new`` is every finding not covered by
    a baseline entry (a key's findings beyond the baselined count are
    new, oldest-line first absorbed); ``stale`` is entries that no
    longer match anything — fixed code whose baseline debt should be
    deleted via --update-baseline."""
    budget: Dict[_Key, int] = Counter()
    for e in entries:
        budget[_entry_key(e)] += int(e.get("count", 1))
    used: Dict[_Key, int] = Counter()
    new: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        k = f.key()
        if used[k] < budget.get(k, 0):
            used[k] += 1
        else:
            new.append(f)
    stale = [e for e in entries
             if used.get(_entry_key(e), 0) == 0]
    return new, stale
