"""Run the rule families (rules/) over source trees.

One entry point for every surface: the ``ptpu check`` CLI, the tier-1
clean-check test (tests/test_check_clean.py), and the analyzer's own
unit tests (which feed snippets through :func:`check_source` under
virtual paths, so path-scoped rules can be exercised without touching
the real tree).

Two kinds of analysis run here.  The per-module families
(rules/ALL_RULES) see one file at a time.  The *program* analyses —
LOCK-ORDER (analysis/lockgraph.py) and THREAD-SHARE
(analysis/threads.py) — see the whole in-scope file set at once
(:data:`lockgraph.PROGRAM_SCOPE`: serving/ plus locksan.py) and are
run by :func:`check_paths` after the per-module pass, or directly via
:func:`check_program` with virtual paths (the fixture tests do this).
Their findings ride the same Finding shape, so suppression, baseline,
text/JSON rendering, and exit semantics need no special cases.

Suppression comments are extracted from the raw source, not the AST:
``# ptpu: ignore[RULE-A,RULE-B]`` on the flagged line or the line
directly above silences those rule ids (``*`` silences all) for that
line.  Findings come back in one stable order — (path, line, rule,
code) — so check output diffs cleanly in review.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Sequence, Set

from .rules import ALL_RULES, Finding, Rule
from . import lockgraph as _lockgraph
from . import threads as _threads

__all__ = ["check_source", "check_file", "check_paths",
           "check_program", "iter_py_files", "PROGRAM_RULE_IDS"]

# The interprocedural families check_program arms (rules/RULE_IDS
# covers the per-module families; the union is the full catalog).
PROGRAM_RULE_IDS = ("LOCK-ORDER", "THREAD-SHARE")

_SUPPRESS = re.compile(r"#\s*ptpu:\s*ignore\[([^\]]*)\]")

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
              ".venv", "venv"}


def _suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """line number (1-based) -> suppressed rule ids, with a comment
    on line N covering findings on N and N+1 (comment-above style)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS.search(line)
        if not m:
            continue
        ids = {tok.strip() for tok in m.group(1).split(",")
               if tok.strip()}
        out.setdefault(i, set()).update(ids)
        out.setdefault(i + 1, set()).update(ids)
    return out


def check_source(source: str, relpath: str,
                 rules: Sequence[Rule] = ALL_RULES) -> List[Finding]:
    """Analyze one module's source under a (possibly virtual) posix
    relpath; returns stably-sorted findings with suppressions
    applied.  Syntax errors surface as one SYNTAX finding rather than
    an exception — a half-written file must not crash the whole
    check."""
    relpath = relpath.replace(os.sep, "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("SYNTAX", relpath, e.lineno or 0, "<module>",
                        (e.text or "").strip(),
                        f"cannot parse: {e.msg}")]
    lines = source.splitlines()
    sup = _suppressions(lines)
    findings: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(relpath):
            continue
        for f in rule.check(tree, lines, relpath):
            ids = sup.get(f.line, ())
            if f.rule in ids or "*" in ids:
                continue
            findings.append(f)
    findings.sort(key=Finding.sort_key)
    return findings


def check_file(path: str, root: str,
               rules: Sequence[Rule] = ALL_RULES) -> List[Finding]:
    relpath = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root))
    with open(path, encoding="utf-8") as f:
        return check_source(f.read(), relpath, rules)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    """Every .py file under ``paths``, first-seen order, deduplicated
    by absolute path — overlapping arguments (``pkg pkg/sub``) must
    not double-count findings, which would both report phantom "new"
    findings on a clean tree and write doubled count budgets into an
    updated baseline."""
    out: List[str] = []
    seen: Set[str] = set()

    def add(f: str) -> None:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            out.append(f)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    add(os.path.join(dirpath, f))
    return out


def check_program(sources: Dict[str, str]) -> List[Finding]:
    """Run the whole-program analyses over the in-scope subset of
    ``sources`` ({relpath: source}) — LOCK-ORDER then THREAD-SHARE —
    with per-line suppressions applied.  Files outside
    :data:`lockgraph.PROGRAM_SCOPE` and files that don't parse are
    dropped silently (the per-module pass already reports SYNTAX)."""
    scoped: Dict[str, str] = {}
    for relpath, src in sources.items():
        rp = relpath.replace(os.sep, "/")
        if not _lockgraph.in_program_scope(rp):
            continue
        try:
            ast.parse(src)
        except SyntaxError:
            continue
        scoped[rp] = src
    if not scoped:
        return []
    model = _lockgraph.build_model(scoped)
    findings = _lockgraph.lock_order_findings(
        _lockgraph.build_lock_graph(model))
    findings += _threads.thread_share_findings(model)
    sup_cache: Dict[str, Dict[int, Set[str]]] = {}
    out: List[Finding] = []
    for f in findings:
        sup = sup_cache.get(f.path)
        if sup is None:
            sup = sup_cache[f.path] = _suppressions(
                scoped.get(f.path, "").splitlines())
        ids = sup.get(f.line, ())
        if f.rule in ids or "*" in ids:
            continue
        out.append(f)
    out.sort(key=Finding.sort_key)
    return out


def check_paths(paths: Iterable[str], root: str = ".",
                rules: Sequence[Rule] = ALL_RULES,
                program: bool = True) -> List[Finding]:
    """Analyze every .py file under ``paths``; findings are reported
    with paths relative to ``root`` and sorted stably.  The
    whole-program families run over the in-scope subset of the same
    file set (``program=False`` restricts to per-module rules)."""
    findings: List[Finding] = []
    sources: Dict[str, str] = {}
    absroot = os.path.abspath(root)
    for path in iter_py_files(paths):
        relpath = os.path.relpath(os.path.abspath(path),
                                  absroot).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(check_source(src, relpath, rules))
        sources[relpath] = src
    if program:
        findings.extend(check_program(sources))
    findings.sort(key=Finding.sort_key)
    return findings
