"""Chrome-trace attribution for ``jax.profiler`` dumps.

``jax.profiler.start_trace(dir)`` writes an xprof session under
``dir/plugins/profile/<ts>/`` whose ``*.trace.json[.gz]`` file is a
Chrome trace-event document: per-device tracks on real hardware
(process names like ``/device:TPU:0``, HLO op events), and — on the
host platform — XLA runtime worker threads (``tf_XLAEigen*`` /
``tf_XLATfrtCpuClient*``) under one ``/host:CPU`` process.  This
module reduces such a document into the attribution record the
serving flight recorder (serving/profiling.py) publishes:

- every selected device/runtime event is CLASSIFIED as ``collective``
  (all-reduce / all-gather / reduce-scatter / all-to-all /
  collective-permute / psum), ``transfer`` (copy / memcpy / infeed /
  outfeed / send / recv), or ``compute`` (everything else — fusions,
  dots, scans);
- per-category busy time is the UNION of event intervals (parallel
  tracks never double-count), with overlaps resolved by priority
  collective > transfer > compute, so the categories PARTITION the
  busy timeline and their shares can never sum past 1.0 of wall;
- ``host_gap`` is the remainder: wall time in the attribution window
  during which NO selected track ran anything — dispatch bubbles,
  host scheduling, admission bookkeeping (arXiv:2011.03641's
  "host-bound" signature).

The attribution window defaults to the span of the serving step
markers (``ptpu_step`` TraceAnnotations, emitted by the slot
managers around every decode dispatch) when present, so the record
measures exactly the profiled step boundaries and not profiler
startup/teardown noise.

Pure stdlib — importable outside serving (offline analysis of a
saved dump: ``python -c "from polyaxon_tpu.analysis.xprof import
attribute_dump; print(attribute_dump('/tmp/prof'))"``) and the unit
layer the synthetic-fixture tests pin (tests/test_profiling.py).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, \
    Tuple

__all__ = ["CATEGORIES", "classify_name", "find_trace_file",
           "load_profile_events", "merge_intervals",
           "subtract_intervals", "attribute_events",
           "attribute_dump", "STEP_MARKER"]

# The TraceAnnotation name the slot managers wrap every decode
# dispatch in (serving/slots.py step_annotation) — the parser's
# window anchor.
STEP_MARKER = "ptpu_step"

# Priority order: an event matching an earlier category never counts
# toward a later one, and overlap between categories resolves the
# same way (see attribute_events).
CATEGORIES = ("collective", "transfer", "compute")

_COLLECTIVE = re.compile(
    r"all[-_ ]?reduce|all[-_ ]?gather|reduce[-_ ]?scatter"
    r"|all[-_ ]?to[-_ ]?all|collective|psum|ppermute"
    r"|(^|[-_ .])permute", re.IGNORECASE)
_TRANSFER = re.compile(
    r"copy|memcpy|infeed|outfeed|(^|[-_ .])(send|recv)($|[-_ .0-9])"
    r"|transfer|h2d|d2h|host[-_ ]?to[-_ ]?device"
    r"|device[-_ ]?to[-_ ]?host", re.IGNORECASE)

# Host-platform fallback: XLA runtime worker threads whose events are
# the closest thing a CPU "device" has to a device track.
_RUNTIME_THREAD = re.compile(r"^tf_")
# ... minus pure bookkeeping noise on those threads: thread-pool
# region markers and waits are idle/overhead, not executed work —
# counting them as compute would report a busy device that is
# actually blocked.
_RUNTIME_NOISE = re.compile(
    r"ThreadpoolListener|TaskDispatcher|dispatch|wait", re.IGNORECASE)


def classify_name(name: str) -> str:
    """collective / transfer / compute for one event name (priority
    order — ``collective-permute-send`` is a collective, not a
    transfer)."""
    if _COLLECTIVE.search(name):
        return "collective"
    if _TRANSFER.search(name):
        return "transfer"
    return "compute"


def find_trace_file(root: str) -> Optional[str]:
    """Newest ``*.trace.json[.gz]`` under ``root`` (an xprof session
    dir, its parent ``--profile-dir``, or any ancestor) — the file
    ``load_profile_events`` wants."""
    pats = ("*.trace.json.gz", "*.trace.json")
    hits: List[str] = []
    for pat in pats:
        hits += glob.glob(os.path.join(root, "**", pat),
                          recursive=True)
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def load_profile_events(path: str) -> List[Dict[str, Any]]:
    """Trace events from a profiler dump: ``path`` may be the trace
    file itself (.json / .json.gz) or a directory to search with
    :func:`find_trace_file`."""
    if os.path.isdir(path):
        f = find_trace_file(path)
        if f is None:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] under {path!r} — did the "
                f"profiler write this dump?")
        path = f
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        doc = json.load(fh)
    evs = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(evs, list):
        raise ValueError(f"{path}: not a Chrome trace document")
    return evs


def merge_intervals(iv: Iterable[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Union of half-open intervals, sorted and coalesced."""
    iv = sorted((a, b) for a, b in iv if b > a)
    out: List[Tuple[float, float]] = []
    for a, b in iv:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def subtract_intervals(iv: Sequence[Tuple[float, float]],
                       sub: Sequence[Tuple[float, float]]
                       ) -> List[Tuple[float, float]]:
    """``iv`` minus ``sub`` (both merged/sorted)."""
    out: List[Tuple[float, float]] = []
    j = 0
    for a, b in iv:
        cur = a
        while j < len(sub) and sub[j][1] <= cur:
            j += 1
        k = j
        while k < len(sub) and sub[k][0] < b:
            s, e = sub[k]
            if s > cur:
                out.append((cur, s))
            cur = max(cur, e)
            if cur >= b:
                break
            k += 1
        if cur < b:
            out.append((cur, b))
    return out


def _span(iv: Sequence[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in iv)


def _clip(iv: Iterable[Tuple[float, float]], lo: float, hi: float
          ) -> List[Tuple[float, float]]:
    return [(max(a, lo), min(b, hi)) for a, b in iv
            if min(b, hi) > max(a, lo)]


def _meta_maps(events: Sequence[Dict[str, Any]]):
    procs: Dict[Any, str] = {}
    threads: Dict[Tuple[Any, Any], str] = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        args = ev.get("args") or {}
        if ev.get("name") == "process_name":
            procs[ev.get("pid")] = str(args.get("name", ""))
        elif ev.get("name") == "thread_name":
            threads[(ev.get("pid"), ev.get("tid"))] = str(
                args.get("name", ""))
    return procs, threads


def attribute_events(events: Sequence[Dict[str, Any]], *,
                     window: Optional[Tuple[float, float]] = None,
                     step_marker: str = STEP_MARKER,
                     max_steps: Optional[int] = None
                     ) -> Dict[str, Any]:
    """Reduce one trace-event list to the per-window attribution
    record (all times in SECONDS):

    - device tracks = processes named ``/device:*`` when any exist
      (real accelerators), else the XLA runtime worker threads
      (``host_fallback: true`` — the honest label for a CPU smoke);
    - window = explicit ``window`` (ts microseconds), else the span
      of ``step_marker`` events — the FIRST ``max_steps`` of them
      when given, so a straggler dispatch that lands its marker
      between a logical window close and the async profiler stop
      cannot stretch the wall — else the span of the selected
      device events;
    - category seconds partition the busy union (priority
      collective > transfer > compute), ``host_gap_s`` is the
      unattributed remainder, so shares sum to exactly 1.0 of wall
      (and each is <= 1.0).
    """
    procs, threads = _meta_maps(events)
    device_pid_set = {pid for pid, name in procs.items()
                      if "/device:" in name}
    device_pids = sorted(str(p) for p in device_pid_set)
    host_fallback = not device_pid_set
    runtime_tids = {key for key, name in threads.items()
                    if _RUNTIME_THREAD.search(name)}

    # One pass, cheap-test-first: a profiled window holds tens of
    # thousands of events (the analyzer competes with the decode
    # loop for the GIL, so this loop's constant factor is the flight
    # recorder's background tax).  ThreadpoolListener bookkeeping is
    # ~95% of a host-platform dump — string-prefix reject it before
    # any regex runs.
    dev: List[Dict[str, Any]] = []
    steps: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("ph") != "X" or "ts" not in ev:
            continue
        name = ev.get("name", "")
        if name == step_marker:
            steps.append(ev)
            continue
        if name.startswith(("ThreadpoolListener", "$")):
            continue        # pool bookkeeping / python host tracer
        if host_fallback:
            if (ev.get("pid"), ev.get("tid")) not in runtime_tids \
                    or _RUNTIME_NOISE.search(name):
                continue
        elif ev.get("pid") not in device_pid_set:
            continue
        dev.append(ev)
    if window is None:
        if steps and max_steps is not None:
            anchor = sorted(steps,
                            key=lambda ev: ev["ts"])[:max_steps]
        else:
            anchor = steps or dev
        if not anchor:
            return {"wall_s": 0.0, "events": 0,
                    "step_markers": 0,
                    "host_fallback": host_fallback,
                    "device_pids": device_pids,
                    "category_s": {c: 0.0 for c in CATEGORIES},
                    "host_gap_s": 0.0,
                    "shares": {c: 0.0 for c in CATEGORIES},
                    "host_gap_share": 0.0,
                    "device_busy_share": 0.0}
        lo = min(ev["ts"] for ev in anchor)
        hi = max(ev["ts"] + ev.get("dur", 0) for ev in anchor)
    else:
        lo, hi = window
    wall_us = max(hi - lo, 1e-9)

    by_cat: Dict[str, List[Tuple[float, float]]] = {
        c: [] for c in CATEGORIES}
    for ev in dev:
        a = ev["ts"]
        b = a + ev.get("dur", 0)
        by_cat[classify_name(ev.get("name", ""))].append((a, b))

    merged = {c: merge_intervals(_clip(by_cat[c], lo, hi))
              for c in CATEGORIES}
    taken: List[Tuple[float, float]] = []
    cat_us: Dict[str, float] = {}
    for c in CATEGORIES:            # priority order
        own = subtract_intervals(merged[c], taken)
        cat_us[c] = _span(own)
        taken = merge_intervals(taken + own)
    busy_us = _span(taken)
    gap_us = max(0.0, wall_us - busy_us)

    wall_s = wall_us / 1e6
    shares = {c: round(cat_us[c] / wall_us, 6) for c in CATEGORIES}
    return {
        "wall_s": round(wall_s, 6),
        "events": len(dev),
        "step_markers": len([ev for ev in steps
                             if lo <= ev["ts"] <= hi]),
        "host_fallback": host_fallback,
        "device_pids": device_pids,
        "category_s": {c: round(cat_us[c] / 1e6, 6)
                       for c in CATEGORIES},
        "host_gap_s": round(gap_us / 1e6, 6),
        "shares": shares,
        "host_gap_share": round(gap_us / wall_us, 6),
        "device_busy_share": round(busy_us / wall_us, 6),
    }


def attribute_dump(path: str, **kw) -> Dict[str, Any]:
    """:func:`attribute_events` over a dump file/dir on disk."""
    return attribute_events(load_profile_events(path), **kw)
