"""Static analysis + runtime sanitizers that machine-check the
serving stack's own invariants (``ptpu check``, docs/ANALYSIS.md).

Three layers, one theme — the conventions PRs 1-4 wrote down in prose
(position-keyed RNG, lock discipline, one compiled program per shape,
no hidden host syncs, no swallowed errors) become checked artifacts:

- :mod:`rules` / :mod:`checker` — the AST linter (`ptpu check`),
  per-module rule families RNG-DET, LOCK-HOLD, JIT-PURITY, HOST-SYNC,
  EXC-SWALLOW, ... with ``# ptpu: ignore[RULE]`` suppressions.
- :mod:`lockgraph` / :mod:`threads` — the whole-program concurrency
  families LOCK-ORDER (static lock-acquisition graph over a call
  graph with held-lock propagation; cycles are potential deadlocks)
  and THREAD-SHARE (attributes written from ≥ 2 inferred thread
  roots with no common lock; ``# ptpu: lockfree[reason]`` sanctions
  by-design sharing).  The committed ``lockorder.json`` is the
  canonical lock-order DAG, and locksan's runtime edges are
  cross-checked against the static graph in the sanitized smoke.
- :mod:`baseline` — the committed, justified list of accepted
  findings; the tier-1 clean-check test holds the package to it.
- :mod:`locksan` / :mod:`recompile` — runtime sanitizers for what
  static analysis can't see: lock-order inversions / long holds, and
  steady-state recompile storms.
- :mod:`xprof` — the ``jax.profiler`` Chrome-trace parser behind the
  serving flight recorder (serving/profiling.py): classifies device
  events into compute/collective/transfer, partitions a window's
  wall into category + host-gap shares.  Pure stdlib, importable for
  offline dump analysis.
"""

from .baseline import (DEFAULT_BASELINE, apply_baseline,
                       load_baseline, save_baseline)
from .checker import (PROGRAM_RULE_IDS, check_file, check_paths,
                      check_program, check_source)
from .locksan import (LOCK_REGISTRY, LockHeldTooLongError,
                      LockOrderError, LockSanitizer, SanitizedLock)
from .recompile import RecompileSentinel
from .rules import ALL_RULES, RULE_IDS, Finding

__all__ = [
    "ALL_RULES", "RULE_IDS", "PROGRAM_RULE_IDS", "Finding",
    "check_source", "check_file", "check_paths", "check_program",
    "DEFAULT_BASELINE", "load_baseline", "save_baseline",
    "apply_baseline",
    "LockSanitizer", "SanitizedLock", "LockOrderError",
    "LockHeldTooLongError", "LOCK_REGISTRY",
    "RecompileSentinel",
]
