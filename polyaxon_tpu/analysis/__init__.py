"""Static analysis + runtime sanitizers that machine-check the
serving stack's own invariants (``ptpu check``, docs/ANALYSIS.md).

Three layers, one theme — the conventions PRs 1-4 wrote down in prose
(position-keyed RNG, lock discipline, one compiled program per shape,
no hidden host syncs, no swallowed errors) become checked artifacts:

- :mod:`rules` / :mod:`checker` — the AST linter (`ptpu check`),
  rule families RNG-DET, LOCK-HOLD, JIT-PURITY, HOST-SYNC,
  EXC-SWALLOW, with ``# ptpu: ignore[RULE]`` suppressions.
- :mod:`baseline` — the committed, justified list of accepted
  findings; the tier-1 clean-check test holds the package to it.
- :mod:`locksan` / :mod:`recompile` — runtime sanitizers for what
  static analysis can't see: lock-order inversions / long holds, and
  steady-state recompile storms.
- :mod:`xprof` — the ``jax.profiler`` Chrome-trace parser behind the
  serving flight recorder (serving/profiling.py): classifies device
  events into compute/collective/transfer, partitions a window's
  wall into category + host-gap shares.  Pure stdlib, importable for
  offline dump analysis.
"""

from .baseline import (DEFAULT_BASELINE, apply_baseline,
                       load_baseline, save_baseline)
from .checker import check_file, check_paths, check_source
from .locksan import (LockHeldTooLongError, LockOrderError,
                      LockSanitizer, SanitizedLock)
from .recompile import RecompileSentinel
from .rules import ALL_RULES, RULE_IDS, Finding

__all__ = [
    "ALL_RULES", "RULE_IDS", "Finding",
    "check_source", "check_file", "check_paths",
    "DEFAULT_BASELINE", "load_baseline", "save_baseline",
    "apply_baseline",
    "LockSanitizer", "SanitizedLock", "LockOrderError",
    "LockHeldTooLongError",
    "RecompileSentinel",
]
