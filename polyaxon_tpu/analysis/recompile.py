"""Recompile sentinel: makes compile-cache misses a first-class,
observable event.

The serving stack's latency story rests on "one compiled program per
(shape, kind)": every program cache (the server's fused/split LRU,
the engine's prefill programs, the slot pool's step/insert programs)
is supposed to go quiet once traffic has warmed its shapes.  A
recompile STORM — an unbounded key (a raw float in a cache key, a
per-request value leaking into a shape) — shows up only as mysterious
tail latency.  The sentinel counts every hit/miss/eviction per cache
kind, exposes them through ``engine/server`` introspection
(``compile_cache_misses`` in /metrics and /info), and optionally
drops a ``compile_miss`` instant event on the telemetry ENGINE track
so /trace and benchmarks/trace_report.py show exactly WHEN each
compile happened relative to the request timeline.

Tests pin the contract directly: after a warmup pass, re-running the
same-shaped plain/sampled/spec co-tenancy schedules must add ZERO
misses (tests/test_analysis.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["RecompileSentinel"]


class RecompileSentinel:
    """Thread-safe hit/miss/eviction counters per cache kind.

    ``telemetry`` is duck-typed (anything with ``.instant``) so this
    module never imports the serving package — the serving package
    imports it."""

    def __init__(self, telemetry=None):
        self._lock = threading.Lock()
        self.telemetry = telemetry
        self.misses = 0
        self.hits = 0
        self.evictions = 0
        self.by_kind: Dict[str, Dict[str, int]] = {}

    def _kind(self, kind: str) -> Dict[str, int]:
        d = self.by_kind.get(kind)
        if d is None:
            d = self.by_kind[kind] = {"misses": 0, "hits": 0,
                                      "evictions": 0}
        return d

    def hit(self, kind: str, key=None) -> None:
        with self._lock:
            self.hits += 1
            self._kind(kind)["hits"] += 1

    def miss(self, kind: str, key=None) -> None:
        with self._lock:
            self.misses += 1
            self._kind(kind)["misses"] += 1
        tel = self.telemetry
        if tel is not None:
            # ENGINE track (pid 2, serving/telemetry.py): compiles
            # interleave visually with the step timeline in /trace.
            tel.instant(0, "compile_miss", time.perf_counter(),
                        pid=2, kind=kind,
                        **({"key": repr(key)[:120]}
                           if key is not None else {}))

    def evicted(self, kind: str, key=None) -> None:
        """An LRU pushed a compiled program out — the NEXT use of its
        shape is a guaranteed miss.  Eviction churn with a steady miss
        count means the cache cap is too small for the live shape
        set."""
        with self._lock:
            self.evictions += 1
            self._kind(kind)["evictions"] += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "compile_cache_misses": self.misses,
                "compile_cache_hits": self.hits,
                "compile_cache_evictions": self.evictions,
                "compile_cache_by_kind":
                    {k: dict(v) for k, v in self.by_kind.items()},
            }
