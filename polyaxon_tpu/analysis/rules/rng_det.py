"""RNG-DET: position-keyed RNG discipline in serving-critical paths."""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


class RngDetRule(Rule):
    """Position-keyed RNG only in serving-critical paths.

    Flags ``jax.random.split`` (any alias ending in ``.split`` whose
    root module is a jax random namespace) and fresh ``PRNGKey(...)``
    construction, UNLESS the key is immediately position-keyed: the
    ``PRNGKey`` call sits inside a ``fold_in(...)`` argument, or is
    assigned to a name that is passed to ``fold_in`` within the same
    function.  Guards the contract that a stream's i-th token key is
    ``fold_in(fold_in(PRNGKey(seed), row), i)`` — a function of the
    request alone — so co-tenancy and admission order can never
    change sampled tokens (docs/SERVING.md)."""

    id = "RNG-DET"

    _SPLIT = re.compile(r"(^|\.)(random|jrandom)\.split$|^jrandom\.split$")

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath) or \
            relpath.endswith("models/generate.py")

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func)
                if name is not None:
                    if rule._SPLIT.search(name):
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            "jax.random.split chains make token "
                            "values depend on the draw schedule; use "
                            "position-keyed fold_in "
                            "(sample_stream_keys)"))
                    elif name.endswith("PRNGKey") and \
                            not self._folded(node):
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            "fresh PRNGKey outside a fold_in: "
                            "serving-path draws must be "
                            "position-keyed (fold_in(PRNGKey(seed), "
                            "row) ... fold_in(base, index))"))
                self.generic_visit(node)

            def _folded(self, node) -> bool:
                # Only fold_in calls in the SAME enclosing function
                # count (module-wide matching would let any unrelated
                # fold_in elsewhere in the file launder a fresh key).
                local = [c for c in self._fold_calls
                         if self._fn_of.get(id(c))
                         is self._fn_of.get(id(node))]
                # (a) nested directly inside a fold_in(...) call
                for anc_call in local:
                    for arg in ast.walk(anc_call):
                        if arg is node:
                            return True
                # (b) assigned to a name folded in the same function
                tgt = self._assign_target(node)
                if tgt is not None:
                    for call in local:
                        for arg in call.args:
                            if isinstance(arg, ast.Name) and \
                                    arg.id == tgt:
                                return True
                return False

            def _assign_target(self, node) -> Optional[str]:
                parent = self._parents.get(node)
                if isinstance(parent, ast.Assign) and \
                        len(parent.targets) == 1 and \
                        isinstance(parent.targets[0], ast.Name):
                    return parent.targets[0].id
                return None

        v = V()
        # Pre-pass: every fold_in call, a child->parent map, and each
        # node's enclosing FunctionDef (lambdas don't open a scope —
        # a fold_in inside a vmapped lambda still belongs to the def
        # that wrote it), so the "immediately folded" exemption can
        # look up and sideways WITHIN one function only.
        v._fold_calls = [
            n for n in ast.walk(tree)
            if isinstance(n, ast.Call)
            and (dotted_name(n.func) or "").endswith("fold_in")]
        v._parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                v._parents[child] = parent

        def fn_of(n):
            n = v._parents.get(n)
            while n is not None and not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                n = v._parents.get(n)
            return n

        v._fn_of = {id(n): fn_of(n) for n in ast.walk(tree)}
        v.visit(tree)
        return findings

RULES = (RngDetRule(),)
