"""EXC-SWALLOW: no silently dropped errors."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _src_line, \
    dotted_name


class ExcSwallowRule(Rule):
    """``except Exception: pass`` — or ``continue`` — (body is only
    control flow) silently drops errors.  The ``continue`` form is
    the loop-sweep variant the request-lifecycle paths invite: an
    eviction/cancellation sweep that swallows per-item errors and
    moves on leaks the very slots it exists to reclaim, invisibly.
    Best-effort teardown belongs in the committed baseline with a
    justification; everything else must at least log at debug level
    so a broken subsystem is diagnosable."""

    id = "EXC-SWALLOW"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_ExceptHandler(self, node):
                if self._broad(node.type) and all(
                        isinstance(s, (ast.Pass, ast.Continue))
                        for s in node.body):
                    what = "continue" if any(
                        isinstance(s, ast.Continue)
                        for s in node.body) else "pass"
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"except-and-{what} drops the error without "
                        f"a trace; log it (debug level is enough) or "
                        f"baseline it as best-effort teardown"))
                self.generic_visit(node)

            @staticmethod
            def _broad(t) -> bool:
                if t is None:
                    return True
                names = [dotted_name(el) for el in t.elts] \
                    if isinstance(t, ast.Tuple) else [dotted_name(t)]
                return any(n in ("Exception", "BaseException")
                           for n in names)

        V().visit(tree)
        return findings

RULES = (ExcSwallowRule(),)
