"""TIME-TRUTH: host-clock deltas must not time async jax dispatch."""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


_CLOCK_CALLS = {"time.perf_counter", "time.time"}
# The sanctioned device-sync spellings: any of these on a line
# between the clock read and the delta makes the delta honest.
_SYNC_TAILS = {"block_until_ready", "device_get"}


class TimeTruthRule(Rule):
    """Host-clock deltas must not time ASYNC jax dispatch.

    ``jax`` dispatch is asynchronous: a jitted call returns device
    futures, so ``t0 = time.perf_counter(); fn(...); dt =
    perf_counter() - t0`` measures how fast the HOST enqueued work,
    not how long the device ran — the number silently shrinks as
    programs grow (more async tail) and every consumer downstream
    (bench rows, step_device_share, SLO math) inherits the lie.
    Flagged: a ``<name> - t0``-style delta whose anchor is a
    ``time.perf_counter()``/``time.time()`` assignment in the same
    function, with at least one jax-rooted call (``jax.*`` /
    ``jnp.*`` / ``jrandom.*``, profiler markers excluded) on the
    lines between anchor and delta and NO ``jax.block_until_ready``
    / ``jax.device_get`` sync in that span.  Scoped to serving/ and
    benchmarks/ — the layers whose timings feed dashboards and
    committed rows.  HTTP/thread timing (no jax call in the span)
    never matches."""

    id = "TIME-TRUTH"

    def applies_to(self, relpath: str) -> bool:
        rp = "/" + relpath.replace("\\", "/")
        return _in_serving(relpath) or "/benchmarks/" in rp

    @staticmethod
    def _call_lines(body: ast.AST):
        """(clock assigns, jax-call lines, sync lines) for one
        function body, NOT descending into nested defs/lambdas (their
        calls run on their own schedule, not between this function's
        clock reads)."""
        anchors: Dict[str, List[int]] = {}
        jax_lines: List[int] = []
        sync_lines: Set[int] = set()

        def scan(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name) \
                        and isinstance(child.value, ast.Call) \
                        and dotted_name(child.value.func) \
                        in _CLOCK_CALLS:
                    anchors.setdefault(child.targets[0].id,
                                       []).append(child.lineno)
                if isinstance(child, ast.Call):
                    name = dotted_name(child.func) or ""
                    tail = name.rsplit(".", 1)[-1]
                    root = name.split(".", 1)[0]
                    if tail in _SYNC_TAILS:
                        sync_lines.add(child.lineno)
                    elif root in ("jax", "jnp", "jrandom") \
                            and not name.startswith("jax.profiler"):
                        jax_lines.append(child.lineno)
                scan(child)

        scan(body)
        return anchors, jax_lines, sync_lines

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_FunctionDef(self, node):
                self._stack.append(node.name)
                anchors, jax_lines, sync_lines = \
                    rule._call_lines(node)
                if anchors:
                    for sub in self._own_nodes(node):
                        if isinstance(sub, ast.BinOp) \
                                and isinstance(sub.op, ast.Sub) \
                                and isinstance(sub.right, ast.Name) \
                                and sub.right.id in anchors:
                            self._check_delta(sub, anchors,
                                              jax_lines, sync_lines)
                self.generic_visit(node)
                self._stack.pop()

            @staticmethod
            def _own_nodes(fn):
                """Walk ``fn``'s body without descending into nested
                defs/lambdas — their deltas anchor (and get checked)
                in their own scope."""
                stack = list(ast.iter_child_nodes(fn))
                while stack:
                    n = stack.pop()
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                        continue
                    yield n
                    stack.extend(ast.iter_child_nodes(n))

            visit_AsyncFunctionDef = visit_FunctionDef

            def _check_delta(self, sub, anchors, jax_lines,
                             sync_lines):
                # Anchor = the nearest clock assignment ABOVE the
                # delta (re-assignment in a loop re-anchors).
                prior = [ln for ln in anchors[sub.right.id]
                         if ln < sub.lineno]
                if not prior:
                    return
                a = max(prior)
                span_jax = [ln for ln in jax_lines
                            if a < ln <= sub.lineno]
                span_sync = any(a < ln <= sub.lineno
                                for ln in sync_lines)
                if span_jax and not span_sync:
                    findings.append(Finding(
                        rule.id, relpath, sub.lineno, self.func,
                        _src_line(lines, sub.lineno),
                        f"host-clock delta over async jax dispatch "
                        f"(jax call at line {span_jax[0]}, no "
                        f"block_until_ready/device_get since the "
                        f"clock read at line {a}): the delta times "
                        f"the ENQUEUE, not the device — sync first, "
                        f"or use the flight recorder's trace "
                        f"attribution for device truth"))

        V().visit(tree)
        return findings

RULES = (TimeTruthRule(),)
