"""The shared jitted-body collector: JIT-PURITY and JIT-DEADLINE
both consume it, so the two rules can never disagree about what
"inside a jitted program" means."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._base import dotted_name


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _collect_jitted(tree: ast.Module):
    """Every jit-wrapped body in a module: decorated defs,
    ``jax.jit(lambda ...)``, and ``jax.jit(fn_name)`` with the name
    resolved LEXICALLY (scope chain from the call site — without
    this, ``jax.jit(step)`` inside a builder method resolves to an
    unrelated same-named METHOD elsewhere in the module and flags
    code that never traces).  Returns ``(jitted_bodies, jit_calls)``:
    ``jitted_bodies`` is ``[(body node, label)]`` deduplicated,
    ``jit_calls`` is ``[(jit Call node, resolved def or None)]`` for
    call-site checks (static_argnums hashability).  Shared by
    JIT-PURITY and JIT-DEADLINE so the two rules can never disagree
    about what "inside a jitted program" means."""
    parents: Dict[ast.AST, ast.AST] = {}
    for p in ast.walk(tree):
        for c in ast.iter_child_nodes(p):
            parents[c] = p
    scopes: Dict[ast.AST, Dict[str, ast.FunctionDef]] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            s = parents.get(n)
            while s is not None and not isinstance(
                    s, (ast.Module, ast.FunctionDef,
                        ast.AsyncFunctionDef, ast.ClassDef)):
                s = parents.get(s)
            scopes.setdefault(s, {})[n.name] = n

    def resolve(call: ast.AST, name: str):
        """Innermost def named ``name`` visible from ``call``."""
        s = parents.get(call)
        while s is not None:
            if isinstance(s, (ast.Module, ast.FunctionDef,
                              ast.AsyncFunctionDef, ast.ClassDef)):
                d = scopes.get(s, {}).get(name)
                if d is not None:
                    return d
            s = parents.get(s)
        return None

    jitted_bodies: List[Tuple[ast.AST, str]] = []
    jit_calls: List[Tuple[ast.Call, Optional[ast.FunctionDef]]] = []
    seen: Set[int] = set()

    def add(node, label):
        if id(node) not in seen:
            seen.add(id(node))
            jitted_bodies.append((node, label))

    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                if _is_jax_jit(dec):
                    add(n, n.name)
                elif isinstance(dec, ast.Call) and (
                        _is_jax_jit(dec.func)
                        or (dotted_name(dec.func) or "").endswith(
                            "partial")
                        and dec.args
                        and _is_jax_jit(dec.args[0])):
                    add(n, n.name)
        elif isinstance(n, ast.Call) and _is_jax_jit(n.func):
            fn = None
            if n.args:
                target = n.args[0]
                if isinstance(target, ast.Lambda):
                    add(target, "<lambda>")
                elif isinstance(target, ast.Name):
                    fn = resolve(n, target.id)
                    if fn is not None:
                        add(fn, target.id)
            jit_calls.append((n, fn))
    return jitted_bodies, jit_calls
