"""PAGE-REF: paged-KV page-pool accounting discipline."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from ._base import (Finding, Rule, _ScopedVisitor, _in_serving,
                    _src_line, dotted_name)


_PAGE_POOL_MODULE = "serving/paged.py"
_PAGE_POOL_LOCK = re.compile(r"(^|_)page_lock$")
_PAGE_INTERNALS = {"refcounts", "_free_pages", "page_tables"}
_PAGE_MUTABLE = {"refcounts", "_free_pages"}
_LIST_MUTATORS = {"append", "pop", "remove", "extend", "insert",
                  "clear"}


class PageRefRule(Rule):
    """Paged-KV page-pool discipline (serving/paged.py).

    The page pool's accounting state — ``refcounts`` and the
    ``_free_pages`` list — is mutated from handler threads (prefix
    pin/unpin) AND the engine thread (admission reserve, eviction
    release), so every mutation must sit under the pool's
    ``_page_lock``; a lockless bump is a lost-update seed that frees
    a page still mapped into a co-tenant's table (the stale-KV leak
    class the page-poison tests pin).  And the pool's internals are
    PRIVATE to the pool module: outside it, code must go through the
    accounting API (``pin``/``unpin``/``try_reserve``/``can_admit``)
    — flagged are (a) inside the pool module, ``refcounts`` /
    ``_free_pages`` mutations not lexically under a ``with
    *page_lock`` block; (b) outside it, ANY access to ``refcounts`` /
    ``_free_pages`` / ``page_tables`` attributes; (c) outside it, raw
    integer page-index literals passed to ``pin``/``unpin`` — page
    ids are pool-issued handles, never constants."""

    id = "PAGE-REF"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        in_pool = relpath.replace("\\", "/").endswith(
            _PAGE_POOL_MODULE)
        parents: Dict[ast.AST, ast.AST] = {}
        for p in ast.walk(tree):
            for c in ast.iter_child_nodes(p):
                parents[c] = p

        def _tail_attr(node) -> Optional[str]:
            """The attribute name at the base of a target chain:
            ``self.refcounts[i]`` -> ``refcounts``."""
            while isinstance(node, ast.Subscript):
                node = node.value
            if isinstance(node, ast.Attribute):
                return node.attr
            return None

        def _locked(node) -> bool:
            """A ``with *page_lock`` ancestor BELOW the nearest
            enclosing function def — a with-block outside the def
            doesn't protect code that runs later."""
            n = parents.get(node)
            while n is not None:
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                    return False
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        name = dotted_name(item.context_expr) or ""
                        if _PAGE_POOL_LOCK.search(
                                name.rsplit(".", 1)[-1]):
                            return True
                n = parents.get(n)
            return False

        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def _flag(self, node, msg):
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno), msg))

            def _check_mutation(self, node, target):
                attr = _tail_attr(target)
                if attr in _PAGE_MUTABLE and not _locked(node):
                    self._flag(
                        node,
                        f"page-pool state ({attr}) mutated outside "
                        f"`with _page_lock`: handler threads and the "
                        f"engine thread race here — a lost update "
                        f"frees a page still mapped by a co-tenant")

            def visit_Assign(self, node):
                if in_pool:
                    for t in node.targets:
                        self._check_mutation(node, t)
                self.generic_visit(node)

            def visit_AnnAssign(self, node):
                if in_pool and node.value is not None:
                    self._check_mutation(node, node.target)
                self.generic_visit(node)

            def visit_AugAssign(self, node):
                if in_pool:
                    self._check_mutation(node, node.target)
                self.generic_visit(node)

            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if in_pool:
                    # free-list mutation via list methods
                    if tail in _LIST_MUTATORS and \
                            isinstance(node.func, ast.Attribute) and \
                            _tail_attr(node.func.value) in \
                            _PAGE_MUTABLE and not _locked(node):
                        self._flag(
                            node,
                            f"free-list .{tail}() outside `with "
                            f"_page_lock`: page allocation must be "
                            f"race-free")
                elif tail in ("pin", "unpin") and \
                        isinstance(node.func, ast.Attribute):
                    for arg in node.args:
                        for el in ast.walk(arg):
                            if isinstance(el, ast.Constant) and \
                                    isinstance(el.value, int) and \
                                    not isinstance(el.value, bool):
                                self._flag(
                                    node,
                                    f"raw page-index literal "
                                    f"{el.value} passed to "
                                    f".{tail}(): page ids are "
                                    f"pool-issued handles, never "
                                    f"constants")
                                break
                        else:
                            continue
                        break
                self.generic_visit(node)

            def visit_Attribute(self, node):
                if not in_pool and node.attr in _PAGE_INTERNALS:
                    self._flag(
                        node,
                        f"page-pool internal .{node.attr} accessed "
                        f"outside the pool module: use the "
                        f"accounting API (pin/unpin/try_reserve/"
                        f"can_admit/page_stats)")
                self.generic_visit(node)

        V().visit(tree)
        return findings

RULES = (PageRefRule(),)
