"""SNAPSHOT-LOCK: the /debug/state consistency contract."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


class SnapshotLockRule(Rule):
    """The ``/debug/state`` consistency contract (docs/DESIGN.md):
    code holding a snapshot-board ``*state_lock`` must never acquire
    the device lock — directly or by calling into a device-
    dispatching entry point.

    The introspection surface exists to answer "why is the engine
    making no progress" — which it cannot do if serving a snapshot
    can queue behind the very device call that is wedged.  Flags,
    inside a ``with <...state_lock>`` body (not descending into
    nested defs):

    - a nested ``with`` on (or blocking ``.acquire()`` of) a lock
      named ``device_lock`` / ``_lock`` — the server's device lock;
    - calls whose dotted tail is a device-dispatching serving entry
      point (``generate`` / ``prefill_prompt`` / ``submit`` /
      ``tick`` / ``_decode_step`` / ``_advance_prefill``);
    - any ``jax.*`` call — snapshot serialization is plain host-dict
      work by contract, so no jax call belongs under the board lock
      (``jax.device_get`` and friends all sync against in-flight
      device work).
    """

    id = "SNAPSHOT-LOCK"

    _DEVICE_ENTRY = frozenset({
        "generate", "prefill_prompt", "submit", "tick",
        "_decode_step", "_advance_prefill"})
    _DEVICE_LOCKS = frozenset({"device_lock", "_lock"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        def _lock_tail(expr) -> str:
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
            return (name or "").rsplit(".", 1)[-1]

        class V(_ScopedVisitor):
            def visit_With(self, node):
                if any(_lock_tail(item.context_expr)
                       .endswith("state_lock")
                       for item in node.items):
                    for stmt in node.body:
                        self._scan(stmt)
                self.generic_visit(node)

            visit_AsyncWith = visit_With

            def _flag(self, node, msg: str) -> None:
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno),
                    f"{msg} while holding the snapshot state lock: "
                    f"/debug/state must answer even when the device "
                    f"is wedged — build the snapshot at a step "
                    f"boundary and serve the published copy "
                    f"(docs/DESIGN.md SNAPSHOT-LOCK)"))

            def _scan(self, node) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return      # runs later, not under the lock
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if _lock_tail(item.context_expr) \
                                in rule._DEVICE_LOCKS:
                            self._flag(item.context_expr,
                                       "acquiring the device lock")
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    tail = name.rsplit(".", 1)[-1]
                    if tail == "acquire" and \
                            isinstance(node.func, ast.Attribute) and \
                            (dotted_name(node.func.value) or "") \
                            .rsplit(".", 1)[-1] in rule._DEVICE_LOCKS:
                        self._flag(node,
                                   "acquiring the device lock")
                    elif tail in rule._DEVICE_ENTRY and \
                            isinstance(node.func, ast.Attribute):
                        self._flag(
                            node,
                            f"calling the device-dispatching entry "
                            f"point .{tail}()")
                    elif name.startswith("jax."):
                        self._flag(node, f"jax call ({name})")
                for child in ast.iter_child_nodes(node):
                    self._scan(child)

        V().visit(tree)
        return findings

RULES = (SnapshotLockRule(),)
