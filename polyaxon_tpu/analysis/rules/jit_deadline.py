"""JIT-DEADLINE: lifecycle control stays host-side — no ``time.*``
calls at all inside jitted programs."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _src_line, dotted_name
from ._jit import _collect_jitted


class DeadlineInJitRule(Rule):
    """Lifecycle control stays HOST-SIDE: no ``time.*`` deadline math
    inside a jit-wrapped step program.

    The request-lifecycle layer (serving/engine.py sweep) delivers
    cancellation, deadline expiry, and preemption at step boundaries
    by comparing host wall-clock against per-group deadlines.  Any
    ``time.*`` call inside a jitted function — not just the clocks
    JIT-PURITY flags, but ALL of the module (``time_ns``,
    ``monotonic_ns``, ``sleep``, ``strftime`` ...) — executes once at
    trace time and freezes into the compiled program: a deadline
    comparison there would evaluate exactly once and never fire
    again, silently turning "evict at the boundary" into "immortal".
    This is the Podracer decoupled-dataflow discipline
    (arXiv:2104.06272): scheduling decisions on the host, pure math
    on the device."""

    id = "JIT-DEADLINE"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        jitted_bodies, _ = _collect_jitted(tree)
        for body, label in jitted_bodies:
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if name.startswith("time."):
                    findings.append(Finding(
                        self.id, relpath, node.lineno, label,
                        _src_line(lines, node.lineno),
                        f"{name}() inside a jitted program: deadline/"
                        f"lifecycle math is host-side scheduling — "
                        f"it freezes at trace time in a compiled "
                        f"step, so a deadline check here would "
                        f"evaluate once and never fire again"))
        return findings

RULES = (DeadlineInJitRule(),)
