"""HOST-SYNC: no implicit device->host syncs in the decode hot path."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _src_line, \
    dotted_name


_JAX_ROOTS = ("jax", "jnp", "jrandom")

_HOT_PATHS = ("serving/engine.py", "serving/slots.py")


def _is_jax_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    root = name.split(".", 1)[0]
    return root in _JAX_ROOTS and not name.endswith("device_get")


class HostSyncRule(Rule):
    """No implicit device->host syncs in the decode hot path.

    ``np.asarray``/``np.array``/``float``/``int`` applied directly to
    a jax-producing call, and ``.tolist()``/``.item()``, each hide a
    ``block_until_ready`` — the decode loop stalls on device work the
    author never sees.  The sanctioned spelling is explicit:
    ``np.asarray(jax.device_get(x))``.  Scoped to the engine step /
    decode modules (serving/engine.py, serving/slots.py) where one
    stray sync costs every resident stream a step."""

    id = "HOST-SYNC"

    def applies_to(self, relpath: str) -> bool:
        return any(relpath.endswith(p) for p in _HOT_PATHS)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if name in ("np.asarray", "np.array", "float",
                            "int") and node.args and \
                        _is_jax_call(node.args[0]):
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"{name}() directly on a jax call is an "
                        f"implicit device->host sync in the decode "
                        f"hot path; spell it jax.device_get(...) so "
                        f"the sync is visible"))
                elif tail in ("tolist", "item") and \
                        isinstance(node.func, ast.Attribute) and \
                        not node.args:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f".{tail}() in the decode hot path is an "
                        f"implicit device->host sync; device_get "
                        f"once, index on the host"))
                self.generic_visit(node)

        V().visit(tree)
        return findings

RULES = (HostSyncRule(),)
