"""LOCK-HOLD: no unbounded blocking inside a ``with <...lock>`` body."""

from __future__ import annotations

import ast
from typing import List

from ._base import (Finding, Rule, _LOCK_NAME, _SOCKET_IO,
                    _ScopedVisitor, _src_line, dotted_name)


class LockHoldRule(Rule):
    """No unbounded blocking inside a ``with <...lock>`` body.

    A serving lock (``device_lock``, ``_lock``, ``_stats_lock``,
    ``_prefix_lock``, anything matching ``*_lock``) serializes every
    handler thread behind its holder: an untimed wait under one turns
    a single slow caller into a server-wide stall, and an inversion-
    prone sleep is a deadlock seed.  Flags, inside such a body (not
    descending into nested function defs, which run later):
    ``time.sleep``; ``.wait()`` / ``.get()`` / ``.join()`` with no
    timeout; socket/HTTP I/O calls; method-form
    ``x.block_until_ready()``.  The functional
    ``jax.block_until_ready(x)`` used to fence a device step is the
    sanctioned sync idiom and is NOT flagged — the step sync is why
    the lock is held at all."""

    id = "LOCK-HOLD"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_With(self, node):
                held = None
                for item in node.items:
                    name = dotted_name(item.context_expr)
                    if name is None and \
                            isinstance(item.context_expr, ast.Call):
                        name = dotted_name(item.context_expr.func)
                    last = (name or "").rsplit(".", 1)[-1]
                    if _LOCK_NAME.search(last):
                        held = last
                        break
                if held is not None:
                    for stmt in node.body:
                        self._scan(stmt, held)
                self.generic_visit(node)

            visit_AsyncWith = visit_With

            def _scan(self, node, held: str) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return          # runs later, not under the lock
                if isinstance(node, ast.Call):
                    self._check_call(node, held)
                for child in ast.iter_child_nodes(node):
                    self._scan(child, held)

            @staticmethod
            def _none_const(a) -> bool:
                return isinstance(a, ast.Constant) and a.value is None

            @staticmethod
            def _true_const(a) -> bool:
                return isinstance(a, ast.Constant) and a.value is True

            def _untimed(self, node: ast.Call, tail: str) -> bool:
                """True when this wait/join/get/wait_for call blocks
                without a bound.  A positional arg is only a timeout
                where the stdlib signature puts one — ``q.get(True)``
                and ``t.join(None)`` are still unbounded."""
                kw = {k.arg: k.value for k in node.keywords}
                timeout = kw.get("timeout")
                if timeout is not None and \
                        not self._none_const(timeout):
                    return False
                if tail in ("wait", "join"):
                    # signature: (timeout=None)
                    return not node.args \
                        or self._none_const(node.args[0])
                if tail == "wait_for":
                    # signature: (predicate, timeout=None)
                    return len(node.args) < 2 \
                        or self._none_const(node.args[1])
                # get: signature (block=True, timeout=None) — only
                # the blocking forms count (q.get(), q.get(True),
                # block=True); d.get(key[, default]) never matches.
                # (acquire shares the (blocking, timeout) shape but
                # has its own check: see _unbounded_acquire.)
                if len(node.args) >= 2 and \
                        not self._none_const(node.args[1]):
                    return False
                blocking = (not node.args and "block" not in kw) \
                    or (node.args and self._true_const(node.args[0])) \
                    or self._true_const(kw.get("block"))
                return bool(blocking)

            @staticmethod
            def _neg_num_const(a) -> bool:
                """A literal negative number (parses as USub over a
                Constant): acquire's spelled-out block-forever."""
                if isinstance(a, ast.UnaryOp) \
                        and isinstance(a.op, ast.USub) \
                        and isinstance(a.operand, ast.Constant):
                    v = a.operand.value
                    return isinstance(v, (int, float)) \
                        and not isinstance(v, bool)
                return False

            def _unbounded_acquire(self, node: ast.Call) -> bool:
                """Lock.acquire(blocking=True, timeout=-1): blocking
                with no timeout.  ``acquire(False)`` (try-lock) and
                an explicit non-literal-negative timeout are bounded
                — but ``timeout=-1`` (or ``acquire(True, -1)``) is
                the stdlib's SPELLED-OUT block-forever and stays
                flagged; a variable timeout gets the benefit of the
                doubt like the rest of the rule."""
                kw = {k.arg: k.value for k in node.keywords}
                if "timeout" in kw:
                    t = kw["timeout"]
                    return self._none_const(t) \
                        or self._neg_num_const(t)
                if len(node.args) >= 2:
                    t = node.args[1]
                    return self._none_const(t) \
                        or self._neg_num_const(t)
                blocking = (not node.args and "blocking" not in kw) \
                    or (node.args
                        and self._true_const(node.args[0])) \
                    or self._true_const(kw.get("blocking"))
                return bool(blocking)

            def _check_call(self, node: ast.Call, held: str) -> None:
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                msg = None
                if name == "time.sleep":
                    msg = "time.sleep while holding"
                elif tail in ("wait", "get", "join", "wait_for") and \
                        isinstance(node.func, ast.Attribute) and \
                        self._untimed(node, tail):
                    msg = f"untimed .{tail}() while holding"
                elif tail == "acquire" and \
                        isinstance(node.func, ast.Attribute) and \
                        _LOCK_NAME.search(
                            (dotted_name(node.func.value) or "")
                            .rsplit(".", 1)[-1]) and \
                        self._unbounded_acquire(node):
                    # Nested blocking lock acquisition under a held
                    # lock is the lock-order-inversion seed the
                    # cancellation/eviction paths must never plant:
                    # `with a_lock: b_lock.acquire()` deadlocks
                    # against any thread doing the reverse.
                    msg = "untimed nested lock .acquire() while " \
                          "holding"
                elif tail == "block_until_ready" and \
                        isinstance(node.func, ast.Attribute) and \
                        dotted_name(node.func.value) not in ("jax",):
                    msg = ("method-form .block_until_ready() while "
                           "holding")
                elif tail in _SOCKET_IO and (
                        name.startswith(("socket.", "requests.",
                                         "urllib.", "http."))
                        or tail in ("urlopen", "create_connection")):
                    msg = f"socket/HTTP I/O ({tail}) while holding"
                if msg is not None:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"{msg} {held}: one slow caller stalls every "
                        f"thread queued on the lock — bound it with a "
                        f"timeout or move it outside the critical "
                        f"section"))

        V().visit(tree)
        return findings

RULES = (LockHoldRule(),)
