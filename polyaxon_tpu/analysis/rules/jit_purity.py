"""JIT-PURITY: no trace-time-frozen impurity inside jitted functions."""

from __future__ import annotations

import ast
import re
from typing import List

from ._base import Finding, Rule, _src_line, dotted_name
from ._jit import _collect_jitted


_IMPURE_CALLS = re.compile(
    r"^(time\.(time|perf_counter|monotonic)"
    r"|np\.random\.\w+|numpy\.random\.\w+"
    r"|random\.\w+)$")


class JitPurityRule(Rule):
    """No trace-time impurity inside jitted functions.

    A ``jax.jit``-wrapped function's Python body runs ONCE, at trace
    time: ``time.time()`` / ``np.random.*`` / stdlib ``random.*``
    results are baked into the compiled program as constants, and
    ``global`` writes happen once per compile, not per call — all
    silent wrong-answer bugs.  Also checks that
    ``static_argnums``/``static_argnames`` targets are hashable by
    construction (an unhashable static arg fails at call time, far
    from the jit site): a targeted parameter whose default is a
    list/dict/set literal is flagged."""

    id = "JIT-PURITY"

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        jitted_bodies, jit_calls = _collect_jitted(tree)
        for call, fn in jit_calls:
            self._check_static_args(call, fn, lines, relpath,
                                    findings)

        for body, label in jitted_bodies:
            for node in ast.walk(body):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    if _IMPURE_CALLS.match(name) and \
                            not name.startswith(("jax.random.",
                                                 "jrandom.")):
                        findings.append(Finding(
                            self.id, relpath, node.lineno, label,
                            _src_line(lines, node.lineno),
                            f"{name}() inside a jitted function runs "
                            f"once at TRACE time and is baked into "
                            f"the program as a constant"))
                elif isinstance(node, ast.Global):
                    findings.append(Finding(
                        self.id, relpath, node.lineno, label,
                        _src_line(lines, node.lineno),
                        "global mutation inside a jitted function "
                        "happens once per compile, not per call"))
        return findings

    def _check_static_args(self, call: ast.Call, fn, lines,
                           relpath, findings) -> None:
        if fn is None:
            return
        params = [a.arg for a in fn.args.args]
        defaults = dict(zip(params[len(params)
                                   - len(fn.args.defaults):],
                            fn.args.defaults))
        marked: List[str] = []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        marked.append(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int) and \
                            el.value < len(params):
                        marked.append(params[el.value])
        for pname in marked:
            default = defaults.get(pname)
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                findings.append(Finding(
                    self.id, relpath, call.lineno, fn.name,
                    _src_line(lines, call.lineno),
                    f"static arg {pname!r} defaults to an unhashable "
                    f"{type(default).__name__.lower()} literal — "
                    f"static_argnums/static_argnames targets must be "
                    f"hashable by construction"))

RULES = (JitPurityRule(),)
