"""WIRE-VERIFY: checksum discipline on wire-payload admission."""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Tuple

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


class WireVerifyRule(Rule):
    """Checksum discipline on wire-payload admission (serving/
    paged.py fleet wire format).

    Every payload that crosses the fleet wire — a ``/prefix/fetch``
    response, a handoff push, a disagg KV admission — is a
    length-prefixed header plus raw C-order buffers, and the header
    carries a crc32 over the buffer body.  The ONLY safe way to
    admit one is ``unpack_spilled``, which verifies that checksum
    and raises the typed ``WirePayloadError`` on mismatch (HTTP 400
    ``payload_integrity``, degrade-to-re-prefill).  A hand-rolled
    decode — ``np.frombuffer`` over wire bytes in a function that
    neither calls ``crc32`` itself nor goes through
    ``unpack_spilled`` — admits whatever a truncated proxy response
    or a torn socket handed it, and the corruption surfaces later as
    silently wrong KV (wrong tokens, not an error).  Flagged in
    serving/: any ``frombuffer`` call whose enclosing function
    contains neither a ``crc32`` call nor an ``unpack_spilled``
    call."""

    id = "WIRE-VERIFY"

    _VERIFIERS = frozenset({"crc32", "unpack_spilled"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self
        # Calls grouped by INNERMOST enclosing def.  The
        # verification scope is the LEXICAL chain: a closure decodes
        # under its enclosing function's crc32 (one body, one
        # payload), but a sibling top-level helper does not — it can
        # be called from anywhere, so a crc32 in one caller blesses
        # nothing.
        scopes: Dict[Tuple[str, ...], Dict[str, Any]] = {}

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                key = tuple(self._stack)
                sc = scopes.setdefault(
                    key, {"func": self.func, "tails": set(),
                          "hits": []})
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                sc["tails"].add(tail)
                if tail == "frombuffer":
                    sc["hits"].append(node)
                self.generic_visit(node)

        V().visit(tree)
        for key, sc in scopes.items():
            if not sc["hits"]:
                continue
            chain_tails = set()
            for k in range(len(key) + 1):
                outer = scopes.get(key[:k])
                if outer is not None:
                    chain_tails |= outer["tails"]
            if rule._VERIFIERS & chain_tails:
                continue
            for node in sc["hits"]:
                findings.append(Finding(
                    rule.id, relpath, node.lineno, sc["func"],
                    _src_line(lines, node.lineno),
                    "frombuffer over wire payload without a "
                    "checksum verify in the same function: admit "
                    "fleet-wire bytes through unpack_spilled (or "
                    "verify crc32 here) — an unverified decode "
                    "turns a truncated/torn transfer into silently "
                    "wrong KV instead of the typed "
                    "payload_integrity degrade"))
        return findings

RULES = (WireVerifyRule(),)
