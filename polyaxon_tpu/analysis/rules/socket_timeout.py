"""SOCKET-TIMEOUT: explicit timeouts on every outbound network call."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


class SocketTimeoutRule(Rule):
    """Explicit timeouts on every outbound network call in serving/.

    The router tier probes replicas and forwards requests over plain
    sockets; a ``socket.create_connection`` / ``urllib.request.
    urlopen`` / ``http.client.HTTPConnection`` call WITHOUT an
    explicit timeout inherits the global default (None = block
    forever) — and a timeout-less probe against a hung replica is
    how the whole ROUTER wedges: one dead endpoint collects the
    probe thread, then the handler threads, and the healthy fleet
    behind the router goes dark with it (the arXiv:2011.03641
    pathology moved up a tier).  Every outbound call must pass
    ``timeout=`` (or the positional timeout its signature defines).

    Flagged call shapes (by trailing name): ``create_connection``
    (timeout is the 2nd positional), ``urlopen`` (3rd), and the
    ``HTTPConnection``/``HTTPSConnection`` constructors (kwarg).  A
    visible timeout — positional in the right slot or ``timeout=``
    anywhere — clears the finding; reading the VALUE is out of scope
    (a named constant is fine, and ``timeout=None`` spelled out at
    least shows intent at the call site)."""

    id = "SOCKET-TIMEOUT"

    # tail -> minimum positional-arg count that covers the timeout
    # slot (0 = keyword-only for this shape).
    _SHAPES = {"create_connection": 2, "urlopen": 3,
               "HTTPConnection": 0, "HTTPSConnection": 0}

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                pos_slot = rule._SHAPES.get(tail)
                if pos_slot is not None:
                    has_kw = any(kw.arg == "timeout"
                                 for kw in node.keywords)
                    has_pos = pos_slot > 0 \
                        and len(node.args) >= pos_slot
                    if not has_kw and not has_pos:
                        findings.append(Finding(
                            rule.id, relpath, node.lineno, self.func,
                            _src_line(lines, node.lineno),
                            f"{tail} without an explicit timeout: "
                            f"the default blocks forever, and a "
                            f"timeout-less probe/forward against a "
                            f"hung replica wedges the router (and "
                            f"every healthy replica behind it) — "
                            f"pass timeout= at the call site"))
                self.generic_visit(node)

        V().visit(tree)
        return findings

RULES = (SocketTimeoutRule(),)
