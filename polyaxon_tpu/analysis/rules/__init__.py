"""Repo-specific static-analysis rule families — the registry.

Each rule family machine-checks one of the serving stack's
written-in-prose contracts (docs/ANALYSIS.md maps every rule to the
contract it guards) and lives in its own module under
``analysis/rules/``; this package assembles them into the one
ordered registry the checker consumes.  Adding a family is: write
``analysis/rules/<family>.py`` exposing a ``RULES`` tuple, append the
module to ``_FAMILY_MODULES`` below, document it in docs/ANALYSIS.md
— no existing module grows.

Per-module rules are AST visitors over one module at a time; they
are deliberately narrow — a rule that cries wolf gets suppressed
into uselessness, so each one flags only the patterns that have
actually bitten (or would bite) this codebase.  The catalog:

- RNG-DET       position-keyed RNG discipline (rng_det.py)
- LOCK-HOLD     no unbounded blocking under a held lock (lock_hold.py)
- JIT-PURITY    no trace-time impurity in jitted bodies (jit_purity.py)
- JIT-DEADLINE  no ``time.*`` at all in jitted programs (jit_deadline.py)
- HOST-SYNC     explicit device->host syncs in the hot path (host_sync.py)
- EXC-SWALLOW   no silently dropped errors (exc_swallow.py)
- PAGE-REF      page-pool accounting discipline (page_ref.py)
- SHARD-LEAK    committed placement on meshes (shard_leak.py)
- TIME-TRUTH    honest host-clock deltas over async jax (time_truth.py)
- SNAPSHOT-LOCK /debug/state never queues behind the device (snapshot_lock.py)
- RETRY-BACKOFF bounded retries only (retry_backoff.py)
- TIER-XFER     page payloads move via the spill tier only (tier_xfer.py)
- SOCKET-TIMEOUT every outbound call carries a timeout (socket_timeout.py)
- WIRE-VERIFY   checksummed wire-payload admission (wire_verify.py)
- PHASE-ENUM    one phase vocabulary, forensics.py's (phase_enum.py)

The interprocedural families LOCK-ORDER and THREAD-SHARE are NOT in
this registry: they analyze the whole serving program at once (call
graph + held-lock propagation) rather than one module, and live in
``analysis/lockgraph.py`` / ``analysis/threads.py``, registered with
the checker as program analyses (checker.PROGRAM_ANALYSES).

Suppression: ``# ptpu: ignore[RULE-A,RULE-B]`` on the flagged line or
the line directly above silences those rules for that line;
``# ptpu: ignore[*]`` silences everything.  Suppressions are for
findings whose justification is local to the code; findings whose
justification is historical (legacy reference paths) belong in the
committed baseline (analysis/baseline.py) with a per-entry
justification.
"""

from __future__ import annotations

from typing import Tuple

from ._base import Finding, Rule, dotted_name
from . import (exc_swallow, host_sync, jit_deadline, jit_purity,
               lock_hold, page_ref, phase_enum, retry_backoff,
               rng_det, shard_leak, snapshot_lock, socket_timeout,
               tier_xfer, time_truth, wire_verify)

__all__ = ["Finding", "Rule", "ALL_RULES", "RULE_IDS", "dotted_name"]

# Registry order is the historical one (it does not affect reported
# findings — those sort by location — but keeps rule listings and
# docs diffs stable).
_FAMILY_MODULES = (rng_det, lock_hold, jit_purity, jit_deadline,
                   host_sync, exc_swallow, page_ref, shard_leak,
                   time_truth, snapshot_lock, retry_backoff,
                   tier_xfer, socket_timeout, wire_verify,
                   phase_enum)

ALL_RULES: Tuple[Rule, ...] = tuple(
    rule for mod in _FAMILY_MODULES for rule in mod.RULES)
RULE_IDS: Tuple[str, ...] = tuple(r.id for r in ALL_RULES)

# Convenience re-exports so `from ..rules import PhaseEnumRule`-style
# imports (tests, tools) keep working across the package split.
_BY_ID = {r.id: type(r) for r in ALL_RULES}
globals().update({cls.__name__: cls for cls in _BY_ID.values()})
