"""RETRY-BACKOFF: bounded-retry discipline in serving/."""

from __future__ import annotations

import ast
from typing import List, Optional

from ._base import (Finding, Rule, _SOCKET_IO, _ScopedVisitor,
                    _in_serving, _src_line, dotted_name)


class RetryBackoffRule(Rule):
    """Bounded-retry discipline in serving/ (docs/SERVING.md "Fault
    tolerance"): an unbounded ``while True`` retry loop around a jax
    or socket call — a broad handler that swallows the error and
    loops again — turns a PERMANENT failure (a dead device, a gone
    peer) into an invisible infinite spin: no error surfaces, no
    counter advances, and the caller hangs forever, which is exactly
    the crash-never anti-pattern the crash-only contract forbids.
    The sanctioned spelling is the shared
    :class:`~polyaxon_tpu.serving.recovery.RetryPolicy`: an attempt
    bound (``max_attempts``) plus jittered backoff (``delay_s``),
    escalating — raising, shedding, or quarantining — once retries
    exhaust.

    Flags, in serving/ only: a constant-true ``while`` loop whose
    body has a ``try`` around a ``jax.*`` or socket/HTTP I/O call
    with a broad handler (bare / ``Exception`` / ``BaseException`` /
    ``OSError`` family) that reaches the next iteration with NO
    bounded escape — no ``raise`` / ``return`` / ``break`` anywhere
    in the handler — while the loop nowhere references the bounded-
    retry spelling (``retry_policy`` / ``max_attempts`` /
    ``delay_s``).  Service loops with external termination
    (``while not self._stop``) are not constant-true and never
    flagged."""

    id = "RETRY-BACKOFF"

    _BROAD = frozenset({"Exception", "BaseException", "OSError",
                        "IOError", "ConnectionError", "TimeoutError",
                        "socket.error", "socket.timeout"})
    _BOUNDED = frozenset({"retry_policy", "max_attempts", "delay_s"})

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        def _walk_no_defs(node):
            """The loop-iteration view: nested defs/lambdas run on
            their own schedule, so nothing inside them retries (or
            bounds) THIS loop."""
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                yield from _walk_no_defs(child)

        def _risky_call(try_node) -> Optional[str]:
            for stmt in try_node.body:
                for n in _walk_no_defs(stmt):
                    if not isinstance(n, ast.Call):
                        continue
                    name = dotted_name(n.func) or ""
                    if name.startswith("jax."):
                        return name
                    if name.rsplit(".", 1)[-1] in _SOCKET_IO:
                        return name or "socket I/O"
            return None

        def _broad(t) -> bool:
            if t is None:
                return True
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            return any((dotted_name(el) or "") in rule._BROAD
                       for el in elts)

        def _escapes(handler) -> bool:
            return any(isinstance(n, (ast.Raise, ast.Return,
                                      ast.Break))
                       for n in _walk_no_defs(handler))

        def _bounded(loop) -> bool:
            for n in _walk_no_defs(loop):
                if isinstance(n, ast.Attribute) \
                        and n.attr in rule._BOUNDED:
                    return True
                if isinstance(n, ast.Name) \
                        and n.id in rule._BOUNDED:
                    return True
            return False

        class V(_ScopedVisitor):
            def visit_While(self, node):
                if isinstance(node.test, ast.Constant) \
                        and bool(node.test.value) \
                        and not _bounded(node):
                    for n in _walk_no_defs(node):
                        if isinstance(n, ast.Try):
                            self._check_try(n)
                self.generic_visit(node)

            def _check_try(self, t) -> None:
                risky = _risky_call(t)
                if risky is None:
                    return
                for h in t.handlers:
                    if _broad(h.type) and not _escapes(h):
                        findings.append(Finding(
                            rule.id, relpath, h.lineno, self.func,
                            _src_line(lines, h.lineno),
                            f"unbounded while-True retry around "
                            f"{risky}: a permanent failure spins "
                            f"forever with no error surfaced — "
                            f"bound it with the shared RetryPolicy "
                            f"(attempt < max_attempts + delay_s "
                            f"backoff; serving/recovery.py) and "
                            f"escalate once retries exhaust"))
                        return

        V().visit(tree)
        return findings

RULES = (RetryBackoffRule(),)
