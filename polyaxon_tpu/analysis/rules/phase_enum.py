"""PHASE-ENUM: closed phase vocabulary for the tail-latency ledger."""

from __future__ import annotations

import ast
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


class PhaseEnumRule(Rule):
    """Closed phase vocabulary for the tail-latency ledger
    (serving/forensics.py).

    The phase ledger's whole value is that every surface — history
    record, ``timings`` block, stitched fleet timeline, /metrics
    gauges, the anomaly sentry — speaks ONE enum: the ``PHASE_*``
    constants in forensics.py.  A consumer that hand-writes
    ``"queue_wait"`` instead of importing ``PHASE_QUEUE_WAIT``
    compiles today and silently stops matching the day the enum is
    renamed or extended — dashboards join on a name that no longer
    exists, and nothing errors.  Flagged in serving/ outside
    forensics.py: any string literal spelling a phase-enum member.

    Deliberately narrow: only the phase names UNIQUE to the ledger
    vocabulary are flagged — ``prefill``/``decode``/``kv_handoff``/
    ``prefill_remote`` double as span names all over the stack and
    cannot be flagged without drowning the signal.  The test suite
    pins this rule's set against the live enum (tests/
    test_analysis.py), so a new phase constant that is not also a
    span name must be added here or the suite fails."""

    id = "PHASE-ENUM"

    # PHASES + ROUTER_PHASES minus the names shared with the span
    # vocabulary (prefill, decode, kv_handoff, prefill_remote).
    _PHASE_LITERALS = frozenset({
        "queue_wait", "device_lock_wait", "admit_wait",
        "kv_wire_fetch", "preempt_gap", "finalize", "unattributed",
        "route_pick", "replica_attempt", "retry_backoff",
    })

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath) \
            and not relpath.endswith("forensics.py")

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Constant(self, node):
                if isinstance(node.value, str) \
                        and node.value in rule._PHASE_LITERALS:
                    findings.append(Finding(
                        rule.id, relpath, node.lineno, self.func,
                        _src_line(lines, node.lineno),
                        f"phase name {node.value!r} written as a "
                        f"string literal: import the PHASE_* "
                        f"constant from serving/forensics.py — a "
                        f"hand-spelled phase silently stops "
                        f"matching when the enum changes (the "
                        f"ledger partition is only auditable "
                        f"because every surface speaks ONE "
                        f"vocabulary)"))
                self.generic_visit(node)

        V().visit(tree)
        return findings

RULES = (PhaseEnumRule(),)
