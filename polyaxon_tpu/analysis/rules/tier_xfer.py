"""TIER-XFER: tiered-KV device<->host transfer discipline."""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


# Identifier shapes that name page-pool payload state: the pools
# themselves (_pool/_draft_pool/pool), page-id collections
# (pages/page_tables/shared_pages), and page-granular leaves.
_TIER_NAMES = re.compile(
    r"(^|_)(pages?|pools?)($|_)|page_table")

# The sanctioned tiered-memory helpers (serving/paged.py): the ONLY
# functions allowed to move page-pool payloads across the
# device<->host boundary.  Matched against the innermost enclosing
# function name.
_TIER_SANCTIONED = {"spill_pages", "rematerialize", "materialize",
                    "_alloc_pool", "scatter_cache"}


class TierXferRule(Rule):
    """Tiered-KV transfer discipline (serving/paged.py host tier).

    The two-tier prefix store moves page payloads device->host only
    through ``spill_pages`` (page-pressure reclaim) and host->device
    only through ``rematerialize``/``scatter_cache`` (prefix-hit
    admission / promotion) — both OFF the decode step path.  A stray
    ``jax.device_put``/``jax.device_get`` whose operand touches
    pool/page state is a page-sized PCIe transfer on whatever path it
    sits; on the step path it is a silent TTFT cliff (and on a mesh,
    an uncommitted placement on top — see SHARD-LEAK).  Flagged in
    serving/ outside the sanctioned helper set."""

    id = "TIER-XFER"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    @staticmethod
    def _touches_pool(node: ast.AST) -> Optional[str]:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) \
                    and _TIER_NAMES.search(n.attr):
                return n.attr
            if isinstance(n, ast.Name) \
                    and _TIER_NAMES.search(n.id):
                return n.id
        return None

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if tail in ("device_put", "device_get"):
                    inner = self._stack[-1] if self._stack else ""
                    if inner not in _TIER_SANCTIONED:
                        for arg in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            hit = rule._touches_pool(arg)
                            if hit:
                                findings.append(Finding(
                                    rule.id, relpath, node.lineno,
                                    self.func,
                                    _src_line(lines, node.lineno),
                                    f"{tail} of page-pool payload "
                                    f"({hit}) outside the sanctioned "
                                    f"tiered-memory helpers "
                                    f"({', '.join(sorted(_TIER_SANCTIONED))})"
                                    f": page-sized device<->host "
                                    f"transfers belong to the spill/"
                                    f"re-materialize tier — on the "
                                    f"step path this is a silent "
                                    f"TTFT cliff"))
                                break
                self.generic_visit(node)

        V().visit(tree)
        return findings

RULES = (TierXferRule(),)
