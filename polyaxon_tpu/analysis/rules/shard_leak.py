"""SHARD-LEAK: meshed-serving placement discipline."""

from __future__ import annotations

import ast
import re
from typing import List

from ._base import Finding, Rule, _ScopedVisitor, _in_serving, \
    _src_line, dotted_name


# Serving KV-pool state attrs whose allocation must flow through the
# mesh-aware allocator helpers (slots._alloc_stacked /
# paged._alloc_pool commit pools to their NamedShardings at birth).
_POOL_STATE_ATTRS = {"_stacked", "_draft_stacked", "_pool",
                     "_draft_pool"}
_ZEROS_FAMILY = {"zeros", "ones", "full", "empty", "zeros_like",
                 "ones_like", "full_like"}
_ALLOC_HELPERS = re.compile(r"(^|\.)(_alloc|_ensure)")


class ShardLeakRule(Rule):
    """Meshed-serving placement discipline (serving/meshed.py).

    A meshed engine's step programs compile with explicit in/out
    shardings over committed operands; a host-built array placed
    UNCOMMITTED (``jax.device_put(x)`` with no sharding) lands on the
    default device, and feeding it to a mesh-compiled program forces
    a transfer/reshard on every call — invisible steady-state tax
    that profiles as mystery step latency.  The sanctioned spellings
    are ``device_put(x, sharding)`` / ``ServingMesh.put_replicated``
    (committed), or keeping the array host-side and letting the
    program's explicit ``in_shardings`` place it.  Pool-state
    allocations (``self._stacked = jnp.zeros(...)``) must go through
    the ``_alloc*``/``_ensure*`` helpers for the same reason: a pool
    born unsharded silently demotes every subsequent step to
    replicated layout."""

    id = "SHARD-LEAK"

    def applies_to(self, relpath: str) -> bool:
        return _in_serving(relpath)

    def check(self, tree, lines, relpath):
        findings: List[Finding] = []
        rule = self

        class V(_ScopedVisitor):
            def _flag(self, node, msg):
                findings.append(Finding(
                    rule.id, relpath, node.lineno, self.func,
                    _src_line(lines, node.lineno), msg))

            def visit_Call(self, node):
                name = dotted_name(node.func) or ""
                tail = name.rsplit(".", 1)[-1]
                if tail == "device_put" and len(node.args) == 1 \
                        and not node.keywords:
                    self._flag(
                        node,
                        "single-argument device_put places the array "
                        "UNCOMMITTED on the default device; fed to a "
                        "mesh-compiled program that costs a transfer "
                        "per call — pass a NamedSharding (or "
                        "ServingMesh.put_replicated)")
                self.generic_visit(node)

            def visit_Assign(self, node):
                if not _ALLOC_HELPERS.search(self.func):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr in _POOL_STATE_ATTRS and \
                                self._allocates(node.value):
                            self._flag(
                                node,
                                f"KV-pool state ({t.attr}) allocated "
                                f"outside the _alloc*/_ensure* "
                                f"helpers: pools must be committed "
                                f"to their mesh shardings at birth "
                                f"(an unsharded pool demotes every "
                                f"step to replicated layout)")
                self.generic_visit(node)

            @staticmethod
            def _allocates(value) -> bool:
                for n in ast.walk(value):
                    if isinstance(n, ast.Call):
                        name = dotted_name(n.func) or ""
                        if name.rsplit(".", 1)[-1] in _ZEROS_FAMILY:
                            return True
                return False

        V().visit(tree)
        return findings

RULES = (ShardLeakRule(),)
