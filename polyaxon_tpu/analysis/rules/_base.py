"""Shared scaffolding for the rule families (`analysis/rules/`).

One Finding shape, one Rule interface, one scoped visitor — every
family module builds on these so the checker, the baseline, and the
suppression machinery never need to know which family produced a
finding.  Helpers that more than one family leans on (dotted-name
resolution, the ``*_lock`` name pattern, the socket-I/O call set)
live here too, so the families can never drift apart on what counts
as "a lock" or "network I/O".
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Finding", "Rule", "dotted_name", "_src_line",
           "_ScopedVisitor", "_in_serving", "_LOCK_NAME",
           "_SOCKET_IO"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``key()`` deliberately excludes the line number: baselines match
    on (rule, path, enclosing function, source text), so edits above
    a baselined finding don't invalidate the whole file's entries.
    """

    rule: str
    path: str       # posix-style path relative to the checked root
    line: int       # 1-based, for humans and editors
    func: str       # enclosing def chain, or "<module>"
    code: str       # stripped source line
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.func, self.code)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.code)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.func}] {self.message}\n    {self.code}")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _src_line(lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


class Rule:
    """One rule family.  Subclasses set ``id`` and implement
    ``applies_to`` (path scoping) and ``check``."""

    id: str = ""
    message: str = ""

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, tree: ast.Module, lines: Sequence[str],
              relpath: str) -> List[Finding]:
        raise NotImplementedError


class _ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the enclosing function-def chain."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def func(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def _in_serving(relpath: str) -> bool:
    return "/serving/" in "/" + relpath


_LOCK_NAME = re.compile(r"(^|_)lock$")

_SOCKET_IO = {"create_connection", "urlopen", "recv", "accept",
              "connect", "sendall", "getresponse", "request"}
