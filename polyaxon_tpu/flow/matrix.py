"""Matrix (hyperparameter search space) schemas.

Parity with the reference's ``V1Matrix*`` kinds (SURVEY.md 2.11; expected at
``polyaxon/_flow/matrix/`` — unverified): grid / random / hyperband / bayes /
hyperopt / iterative / mapping, plus hp-distribution vocabulary and early
stopping policies.  The algorithms themselves live in ``polyaxon_tpu.tune``;
these schemas are the declarative surface.
"""

from __future__ import annotations

from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import field_validator

from .base import BaseSchema

# ---------------------------------------------------------------------------
# HP distributions
# ---------------------------------------------------------------------------


class V1HpChoice(BaseSchema):
    kind: Literal["choice"] = "choice"
    value: List[Any]


class V1HpPChoice(BaseSchema):
    """Weighted choice: value is a list of [option, probability] pairs."""

    kind: Literal["pchoice"] = "pchoice"
    value: List[Any]

    @field_validator("value")
    @classmethod
    def _check(cls, v):
        total = 0.0
        for pair in v:
            if not isinstance(pair, (list, tuple)) or len(pair) != 2:
                raise ValueError("pchoice entries must be [option, prob] pairs")
            try:
                prob = float(pair[1])
            except (TypeError, ValueError):
                raise ValueError(
                    f"pchoice probability must be a number, got {pair[1]!r}"
                )
            if prob < 0:
                raise ValueError(f"pchoice probability must be >= 0, got {prob}")
            total += prob
        if abs(total - 1.0) > 1e-6:
            raise ValueError(f"pchoice probabilities must sum to 1, got {total}")
        return v


class V1HpRange(BaseSchema):
    kind: Literal["range"] = "range"
    value: Any  # [start, stop, step] or {"start":..,"stop":..,"step":..}

    def as_tuple(self):
        v = self.value
        if isinstance(v, dict):
            return v["start"], v["stop"], v.get("step", 1)
        if isinstance(v, str):
            parts = [float(x) for x in v.split(":")]
            return tuple(parts) if len(parts) == 3 else (*parts, 1)
        return v[0], v[1], (v[2] if len(v) > 2 else 1)


class _SpaceDist(BaseSchema):
    value: Any  # [start, stop, num] | {"start":..} | "start:stop:num"

    def as_tuple(self):
        v = self.value
        if isinstance(v, dict):
            return v["start"], v["stop"], int(v.get("num", 10))
        if isinstance(v, str):
            parts = v.split(":")
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"{self.kind} expects 'start:stop:num', got {v!r}")
            start, stop = float(parts[0]), float(parts[1])
            num = int(parts[2]) if len(parts) == 3 else 10
            return start, stop, num
        return v[0], v[1], int(v[2])


class V1HpLinSpace(_SpaceDist):
    kind: Literal["linspace"] = "linspace"


class V1HpLogSpace(_SpaceDist):
    kind: Literal["logspace"] = "logspace"


class V1HpGeomSpace(_SpaceDist):
    kind: Literal["geomspace"] = "geomspace"


class _Dist2(BaseSchema):
    value: Any  # [low, high] or {"low":..,"high":..}

    def as_tuple(self):
        v = self.value
        if isinstance(v, dict):
            if "low" in v:
                return v["low"], v["high"]
            return v["loc"], v["scale"]
        return v[0], v[1]


class V1HpUniform(_Dist2):
    kind: Literal["uniform"] = "uniform"


class V1HpQUniform(_Dist2):
    kind: Literal["quniform"] = "quniform"


class V1HpLogUniform(_Dist2):
    kind: Literal["loguniform"] = "loguniform"


class V1HpQLogUniform(_Dist2):
    kind: Literal["qloguniform"] = "qloguniform"


class V1HpNormal(_Dist2):
    kind: Literal["normal"] = "normal"


class V1HpQNormal(_Dist2):
    kind: Literal["qnormal"] = "qnormal"


class V1HpLogNormal(_Dist2):
    kind: Literal["lognormal"] = "lognormal"


class V1HpQLogNormal(_Dist2):
    kind: Literal["qlognormal"] = "qlognormal"


V1HpParam = Union[
    V1HpChoice, V1HpPChoice, V1HpRange, V1HpLinSpace, V1HpLogSpace,
    V1HpGeomSpace, V1HpUniform, V1HpQUniform, V1HpLogUniform,
    V1HpQLogUniform, V1HpNormal, V1HpQNormal, V1HpLogNormal, V1HpQLogNormal,
]

HP_BY_KIND = {
    "choice": V1HpChoice, "pchoice": V1HpPChoice, "range": V1HpRange,
    "linspace": V1HpLinSpace, "logspace": V1HpLogSpace,
    "geomspace": V1HpGeomSpace, "uniform": V1HpUniform,
    "quniform": V1HpQUniform, "loguniform": V1HpLogUniform,
    "qloguniform": V1HpQLogUniform, "normal": V1HpNormal,
    "qnormal": V1HpQNormal, "lognormal": V1HpLogNormal,
    "qlognormal": V1HpQLogNormal,
}

# Distributions a grid search can enumerate exhaustively.
DISCRETE_KINDS = {"choice", "range", "linspace", "logspace", "geomspace"}


def parse_hp_params(data: Optional[Dict[str, Any]]) -> Optional[Dict[str, V1HpParam]]:
    if data is None:
        return None
    out = {}
    for name, spec in data.items():
        if isinstance(spec, dict):
            kind = spec.get("kind")
            cls = HP_BY_KIND.get(kind)
            if cls is None:
                raise ValueError(f"Unknown hp kind {kind!r} for param {name!r}")
            out[name] = cls.from_dict(spec)
        else:
            out[name] = spec
    return out


# ---------------------------------------------------------------------------
# Early stopping
# ---------------------------------------------------------------------------


class V1MetricEarlyStopping(BaseSchema):
    kind: Literal["metric_early_stopping"] = "metric_early_stopping"
    metric: str
    value: float
    optimization: Literal["maximize", "minimize"] = "maximize"
    policy: Optional[Dict[str, Any]] = None


class V1FailureEarlyStopping(BaseSchema):
    kind: Literal["failure_early_stopping"] = "failure_early_stopping"
    percent: float


V1EarlyStopping = Union[V1MetricEarlyStopping, V1FailureEarlyStopping]


class V1OptimizationMetric(BaseSchema):
    name: str
    optimization: Literal["maximize", "minimize"] = "maximize"

    def is_better(self, a: float, b: float) -> bool:
        """True if a is strictly better than b."""
        return a > b if self.optimization == "maximize" else a < b


class V1OptimizationResource(BaseSchema):
    """Hyperband resource axis (e.g. epochs or steps)."""

    name: str
    type: Literal["int", "float"] = "int"

    def cast(self, v):
        return int(v) if self.type == "int" else float(v)


# ---------------------------------------------------------------------------
# Matrix kinds
# ---------------------------------------------------------------------------


class V1GridSearch(BaseSchema):
    kind: Literal["grid"] = "grid"
    params: Dict[str, Any]
    num_runs: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        parsed = parse_hp_params(v)
        for name, hp in (parsed or {}).items():
            kind = getattr(hp, "kind", None)
            if kind is not None and kind not in DISCRETE_KINDS:
                raise ValueError(
                    f"Grid search param {name!r} uses continuous distribution "
                    f"{kind!r}; grid requires one of {sorted(DISCRETE_KINDS)}"
                )
        return parsed


class V1RandomSearch(BaseSchema):
    kind: Literal["random"] = "random"
    params: Dict[str, Any]
    num_runs: int = 10
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Hyperband(BaseSchema):
    """Successive-halving brackets (Li et al.): parity with reference
    hyperband bracket/rung math (SURVEY.md 2.11/3.3)."""

    kind: Literal["hyperband"] = "hyperband"
    params: Dict[str, Any]
    max_iterations: int
    eta: float = 3
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    resume: Optional[bool] = None
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Asha(BaseSchema):
    """Asynchronous successive halving (Li et al. 2020) — barrier-free
    promotions, built for straggler-heavy TPU fleets (preemptions,
    queue delays).  An ADDITION over the reference's matrix kinds
    (SURVEY.md 2.11 tops out at hyperband); the synchronous math lives
    in tune/hyperband.py, the async manager in tune/asha.py."""

    kind: Literal["asha"] = "asha"
    params: Dict[str, Any]
    num_runs: int
    max_iterations: int
    eta: float = 3
    min_resource: float = 1
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Bayes(BaseSchema):
    kind: Literal["bayes"] = "bayes"
    params: Dict[str, Any]
    num_initial_runs: int = 5
    max_iterations: int = 10
    metric: V1OptimizationMetric
    utility_function: Optional[Dict[str, Any]] = None
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Hyperopt(BaseSchema):
    """TPE-style search (reference delegates to hyperopt; we implement TPE
    natively in ``polyaxon_tpu.tune.tpe``)."""

    kind: Literal["hyperopt"] = "hyperopt"
    params: Dict[str, Any]
    num_runs: int = 10
    max_iterations: Optional[int] = None
    algorithm: Literal["tpe", "rand", "anneal"] = "tpe"
    metric: Optional[V1OptimizationMetric] = None
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Iterative(BaseSchema):
    """User-driven iterative tuning: a tuner container proposes suggestions."""

    kind: Literal["iterative"] = "iterative"
    params: Dict[str, Any]
    max_iterations: int
    seed: Optional[int] = None
    tuner: Optional[Dict[str, Any]] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None

    @field_validator("params")
    @classmethod
    def _parse(cls, v):
        return parse_hp_params(v)


class V1Mapping(BaseSchema):
    """Explicit list of param dicts — one child run per entry."""

    kind: Literal["mapping"] = "mapping"
    values: List[Dict[str, Any]]
    concurrency: Optional[int] = None
    early_stopping: Optional[List[V1EarlyStopping]] = None


V1Matrix = Union[
    V1GridSearch, V1RandomSearch, V1Hyperband, V1Asha, V1Bayes,
    V1Hyperopt, V1Iterative, V1Mapping,
]

MATRIX_BY_KIND = {
    "grid": V1GridSearch,
    "random": V1RandomSearch,
    "hyperband": V1Hyperband,
    "asha": V1Asha,
    "bayes": V1Bayes,
    "hyperopt": V1Hyperopt,
    "iterative": V1Iterative,
    "mapping": V1Mapping,
}


def parse_matrix(data):
    if data is None or not isinstance(data, dict):
        return data
    kind = data.get("kind")
    cls = MATRIX_BY_KIND.get(kind)
    if cls is None:
        raise ValueError(
            f"Unknown matrix kind {kind!r}; expected one of {sorted(MATRIX_BY_KIND)}"
        )
    return cls.from_dict(data)


