"""Slim typed pods-and-containers vocabulary used by environments/containers.

The reference leans on full Kubernetes client models; we keep a minimal,
validated subset sufficient for the converter (SURVEY.md 2.10) while
remaining open (extra fields allowed) so real k8s YAML passes through.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pydantic import field_validator

from .base import BaseOpenSchema


class V1EnvVar(BaseOpenSchema):
    name: str
    value: Optional[str] = None
    value_from: Optional[Dict[str, Any]] = None


class V1ResourceRequirements(BaseOpenSchema):
    limits: Optional[Dict[str, Any]] = None
    requests: Optional[Dict[str, Any]] = None


class V1VolumeMount(BaseOpenSchema):
    name: str
    mount_path: Optional[str] = None
    sub_path: Optional[str] = None
    read_only: Optional[bool] = None


class V1ContainerPort(BaseOpenSchema):
    container_port: int
    name: Optional[str] = None
    host_port: Optional[int] = None


class V1Container(BaseOpenSchema):
    """Main/init/sidecar container spec."""

    name: Optional[str] = None
    image: Optional[str] = None
    image_pull_policy: Optional[str] = None
    command: Optional[List[str]] = None
    args: Optional[List[str]] = None
    env: Optional[List[V1EnvVar]] = None
    resources: Optional[V1ResourceRequirements] = None
    volume_mounts: Optional[List[V1VolumeMount]] = None
    working_dir: Optional[str] = None
    ports: Optional[List[V1ContainerPort]] = None

    @field_validator("command", "args", mode="before")
    @classmethod
    def _stringify(cls, v):
        # Template resolution yields native types ({{ epochs }} -> 4); exec
        # argv is strings.  Use YAML/JSON spellings (true, not True; JSON
        # for containers) so programs parse what the spec author wrote.
        import json

        def conv(x):
            if isinstance(x, str):
                return x
            if x is None:
                return ""
            if isinstance(x, bool):
                return "true" if x else "false"
            if isinstance(x, (dict, list)):
                return json.dumps(x)
            return str(x)

        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        return v

    def get_resources(self) -> V1ResourceRequirements:
        return self.resources or V1ResourceRequirements()
