"""IO (inputs/outputs) and param schemas.

Capability parity with the reference's ``V1IO``/``V1Param`` (SURVEY.md 2.3;
expected reference location ``polyaxon/_flow/io/`` — unverified).  An IO
declares a typed input/output of a component; a param supplies a value (or a
reference to another run's output / dag / matrix context) for it.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Union

from pydantic import field_validator

from .base import BaseSchema

# Supported IO types and their python validators.
IO_TYPES = {
    "int": int,
    "float": float,
    "bool": bool,
    "str": str,
    "dict": dict,
    "list": list,
    "path": str,
    "uri": str,
    "auth": dict,
    "git": dict,
    "image": str,
    "dockerfile": str,
    "event": dict,
    "artifacts": dict,
    "tensorboard": str,
    "any": object,
}

REF_RE = re.compile(r"^(runs\.[\w-]+|ops\.[\w-]+|dag|matrix|globals)$")
# Canonical template pattern; the compiler's template engine imports this.
TEMPLATE_RE = re.compile(r"{{\s*(.*?)\s*}}")


def check_declared_params(names, declared, out_names, owner: str = "component"):
    """Raise if any supplied param name is not a declared input/output."""
    for name in names:
        if name not in declared and name not in out_names:
            raise ValueError(
                f"Param {name!r} is not declared as an input/output of {owner}"
            )


def fill_default_params(declared, resolved, owner: str = "component",
                        require: bool = True):
    """Fill IO defaults into ``resolved``; raise on missing required inputs."""
    for name, io in declared.items():
        if name in resolved:
            continue
        if io.value is not None:
            resolved[name] = io.value
        elif not io.is_optional and require:
            raise ValueError(
                f"Input {name!r} of {owner} is required but no param was "
                "given and it has no default"
            )
    return resolved


def check_io_value(value: Any, type_: Optional[str]) -> bool:
    """True if ``value`` conforms to declared IO ``type_``."""
    if type_ is None or type_ == "any" or value is None:
        return True
    expected = IO_TYPES.get(type_)
    if expected is None:
        raise ValueError(f"Unknown IO type: {type_!r}")
    if expected is object:
        return True
    if expected is float and isinstance(value, int) and not isinstance(value, bool):
        return True
    if expected is int and isinstance(value, bool):
        return False
    return isinstance(value, expected)


def parse_io_value(value: Any, type_: Optional[str]) -> Any:
    """Coerce a (possibly string) param value to the IO's declared type."""
    if value is None or type_ in (None, "any"):
        return value
    if isinstance(value, str):
        try:
            if type_ == "int":
                return int(value)
            if type_ == "float":
                return float(value)
            if type_ == "bool":
                if value.lower() in ("true", "1", "yes", "on"):
                    return True
                if value.lower() in ("false", "0", "no", "off"):
                    return False
                raise ValueError(value)
            if type_ in ("dict", "list"):
                import json

                parsed = json.loads(value)
                if not check_io_value(parsed, type_):
                    raise ValueError(value)
                return parsed
        except ValueError as e:
            raise ValueError(
                f"Value {value!r} cannot be parsed as IO type {type_!r}"
            ) from e
    if not check_io_value(value, type_):
        raise ValueError(f"Value {value!r} is not a valid {type_!r}")
    return value


class V1IO(BaseSchema):
    """A typed input or output declaration on a component."""

    name: str
    description: Optional[str] = None
    type: Optional[str] = None
    value: Optional[Any] = None
    is_optional: Optional[bool] = None
    is_list: Optional[bool] = None
    is_flag: Optional[bool] = None
    arg_format: Optional[str] = None
    connection: Optional[str] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None
    options: Optional[List[Any]] = None

    @field_validator("type")
    @classmethod
    def _check_type(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v not in IO_TYPES:
            raise ValueError(f"Unknown IO type {v!r}; expected one of {sorted(IO_TYPES)}")
        return v

    def validate_value(self, value: Any) -> Any:
        if self.is_list:
            if not isinstance(value, list):
                raise ValueError(f"IO {self.name!r} expects a list, got {value!r}")
            return [self._validate_one(v) for v in value]
        return self._validate_one(value)

    def _validate_one(self, value: Any) -> Any:
        value = parse_io_value(value, self.type)
        if self.options and value not in self.options:
            raise ValueError(
                f"IO {self.name!r} value {value!r} not in options {self.options}"
            )
        return value


class V1Param(BaseSchema):
    """A value (or reference) supplied for a component input.

    ``ref`` points at another entity whose output is resolved at compile
    time: ``runs.<uuid>``, ``ops.<name>`` (dag sibling), ``dag``,
    ``matrix``, or ``globals``.
    """

    value: Optional[Any] = None
    ref: Optional[str] = None
    context_only: Optional[bool] = None
    connection: Optional[str] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None

    @field_validator("ref")
    @classmethod
    def _check_ref(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and not REF_RE.match(v):
            raise ValueError(
                f"Invalid param ref {v!r}: expected runs.<uuid>, ops.<name>, "
                "dag, matrix, or globals"
            )
        return v

    @property
    def is_literal(self) -> bool:
        return self.ref is None and not (
            isinstance(self.value, str) and TEMPLATE_RE.search(self.value)
        )

    @property
    def is_template(self) -> bool:
        return isinstance(self.value, str) and bool(TEMPLATE_RE.search(self.value))


def params_from_dict(data: Optional[Dict[str, Any]]) -> Dict[str, V1Param]:
    """Normalize a params mapping: bare literals become V1Param(value=...).

    Caller-supplied V1Param instances are copied so later validation/coercion
    never mutates objects the caller may reuse across operations.
    """
    out: Dict[str, V1Param] = {}
    for name, spec in (data or {}).items():
        if isinstance(spec, V1Param):
            out[name] = spec.model_copy(deep=True)
        elif isinstance(spec, dict) and ("value" in spec or "ref" in spec):
            out[name] = V1Param.from_dict(spec)
        else:
            out[name] = V1Param(value=spec)
    return out
