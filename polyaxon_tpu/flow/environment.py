"""Environment / termination / plugins / init / cache / build / hooks schemas.

Parity targets: reference ``V1Environment``, ``V1Termination``, ``V1Plugins``,
``V1Init``, ``V1Cache``, ``V1Hook`` (SURVEY.md 2.3; expected reference
location ``polyaxon/_flow/`` — unverified).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from .base import BaseOpenSchema, BaseSchema
from .k8s_refs import V1Container


class V1Environment(BaseOpenSchema):
    """Pod-level scheduling knobs for a run."""

    labels: Optional[Dict[str, str]] = None
    annotations: Optional[Dict[str, str]] = None
    node_selector: Optional[Dict[str, str]] = None
    affinity: Optional[Dict[str, Any]] = None
    tolerations: Optional[List[Dict[str, Any]]] = None
    node_name: Optional[str] = None
    service_account_name: Optional[str] = None
    host_aliases: Optional[List[Dict[str, Any]]] = None
    security_context: Optional[Dict[str, Any]] = None
    image_pull_secrets: Optional[List[str]] = None
    host_network: Optional[bool] = None
    host_pid: Optional[bool] = None
    dns_policy: Optional[str] = None
    dns_config: Optional[Dict[str, Any]] = None
    scheduler_name: Optional[str] = None
    priority_class_name: Optional[str] = None
    priority: Optional[int] = None
    restart_policy: Optional[str] = None


class V1Termination(BaseSchema):
    """Retry/timeout/TTL policy enforced by the operator-equivalent."""

    max_retries: Optional[int] = None
    ttl: Optional[int] = None
    timeout: Optional[int] = None


class V1Plugins(BaseSchema):
    """Feature toggles controlling auxiliaries injected by the converter."""

    auth: Optional[bool] = None
    docker: Optional[bool] = None
    shm: Optional[bool] = None
    mount_artifacts_store: Optional[bool] = None
    collect_artifacts: Optional[bool] = None
    collect_logs: Optional[bool] = None
    collect_resources: Optional[bool] = None
    sync_statuses: Optional[bool] = None
    auto_resume: Optional[bool] = None
    log_level: Optional[str] = None
    side_car: Optional[Dict[str, Any]] = None
    external_host: Optional[bool] = None
    sidecar: Optional[Dict[str, Any]] = None


class V1GitInit(BaseSchema):
    url: Optional[str] = None
    revision: Optional[str] = None
    flags: Optional[List[str]] = None


class V1ArtifactsInit(BaseSchema):
    files: Optional[List[Any]] = None
    dirs: Optional[List[Any]] = None
    workers: Optional[int] = None


class V1DockerfileInit(BaseOpenSchema):
    image: Optional[str] = None
    env: Optional[Dict[str, str]] = None
    run: Optional[List[str]] = None
    filename: Optional[str] = None
    workdir: Optional[str] = None
    copy_: Optional[List[Any]] = None


class V1FileInit(BaseSchema):
    content: Optional[str] = None
    filename: Optional[str] = None
    kind: Optional[str] = None
    chmod: Optional[str] = None


class V1TensorboardInit(BaseSchema):
    port: Optional[int] = None
    uuids: Optional[List[str]] = None
    use_names: Optional[bool] = None
    path_prefix: Optional[str] = None


class V1Init(BaseSchema):
    """One init action: git clone, artifact pull, dockerfile gen, inline file,
    or a custom init container — run before the main container starts."""

    git: Optional[V1GitInit] = None
    artifacts: Optional[V1ArtifactsInit] = None
    dockerfile: Optional[V1DockerfileInit] = None
    file: Optional[V1FileInit] = None
    tensorboard: Optional[V1TensorboardInit] = None
    lineage_ref: Optional[str] = None
    model_ref: Optional[str] = None
    artifact_ref: Optional[str] = None
    connection: Optional[str] = None
    path: Optional[str] = None
    container: Optional[V1Container] = None

    def has_connection(self) -> bool:
        return bool(self.connection)


class V1Cache(BaseSchema):
    disable: Optional[bool] = None
    ttl: Optional[int] = None
    io_keys: Optional[List[str]] = None
    sections: Optional[List[str]] = None


class V1Hook(BaseSchema):
    """Post-run action (e.g. notify or launch another component)."""

    connection: Optional[str] = None
    trigger: Optional[str] = None  # succeeded | failed | stopped | done
    hub_ref: Optional[str] = None
    conditions: Optional[str] = None
    queue: Optional[str] = None
    presets: Optional[List[str]] = None
    params: Optional[Dict[str, Any]] = None
    disable_defaults: Optional[bool] = None


class V1Build(BaseSchema):
    """Pre-run image build directive."""

    hub_ref: Optional[str] = None
    connection: Optional[str] = None
    queue: Optional[str] = None
    presets: Optional[List[str]] = None
    params: Optional[Dict[str, Any]] = None
    run_patch: Optional[Dict[str, Any]] = None
    patch_strategy: Optional[str] = None


class V1Notification(BaseSchema):
    connections: List[str]
    trigger: Optional[str] = None
