"""Component / Operation / CompiledOperation schemas.

Parity targets: reference ``V1Component``, ``V1Operation``,
``V1CompiledOperation`` (SURVEY.md 2.3/2.6; expected at
``polyaxon/_flow/component.py`` / ``operations/`` — unverified).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from pydantic import field_validator, model_validator

from .base import BaseSchema, patch_dict
from .environment import V1Build, V1Cache, V1Hook, V1Plugins, V1Termination
from .io import V1IO, V1Param, params_from_dict
from .matrix import V1Matrix, parse_matrix
from .run import (
    RunKind,
    V1Runtime,
    V1Schedule,
    parse_runtime,
    parse_schedule,
)

SPEC_VERSION = 1.1


class V1Join(BaseSchema):
    """Collect upstream runs matching a query into a param (fan-in)."""

    query: str
    sort: Optional[str] = None
    limit: Optional[int] = None
    offset: Optional[int] = None
    params: Optional[Dict[str, V1Param]] = None

    @field_validator("params", mode="before")
    @classmethod
    def _params(cls, v):
        return params_from_dict(v) if v is not None else None


class V1Component(BaseSchema):
    """A reusable, typed, runnable unit: IO contract + runtime."""

    version: Optional[float] = None
    kind: str = "component"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[List[str]] = None
    presets: Optional[List[str]] = None
    queue: Optional[str] = None
    priority: Optional[int] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[List[V1Hook]] = None
    inputs: Optional[List[V1IO]] = None
    outputs: Optional[List[V1IO]] = None
    template: Optional[Dict[str, Any]] = None
    run: Optional[Any] = None

    @field_validator("kind")
    @classmethod
    def _kind(cls, v):
        if v != "component":
            raise ValueError(f"Expected kind 'component', got {v!r}")
        return v

    @field_validator("run", mode="before")
    @classmethod
    def _run(cls, v):
        return parse_runtime(v)

    def get_io(self, name: str) -> Optional[V1IO]:
        for io in (self.inputs or []) + (self.outputs or []):
            if io.name == name:
                return io
        return None

    def validate_params(self, params: Optional[Dict[str, Any]],
                        is_template: bool = False) -> Dict[str, V1Param]:
        """Check supplied params against the IO contract; fill defaults.

        Returns the full resolved param dict (including defaulted inputs).
        Raises on unknown params, missing required inputs, or type errors.
        """
        from .io import check_declared_params, fill_default_params

        params = params_from_dict(params)
        declared = {io.name: io for io in (self.inputs or [])}
        out_names = {io.name for io in (self.outputs or [])}
        owner = f"component {self.name!r}"

        check_declared_params(
            [n for n, p in params.items() if not p.context_only],
            declared, out_names, owner,
        )
        for name, param in params.items():
            io = declared.get(name)
            if io is not None and param.is_literal and param.value is not None:
                param.value = io.validate_value(param.value)

        filled = fill_default_params(
            declared, {n: p for n, p in params.items()}, owner,
            require=not is_template,
        )
        for name, value in filled.items():
            if name not in params:
                params[name] = V1Param(value=value)
        return params


class V1Operation(BaseSchema):
    """An invocation of a component with params/overrides/matrix/schedule."""

    version: Optional[float] = None
    kind: str = "operation"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[List[str]] = None
    presets: Optional[List[str]] = None
    queue: Optional[str] = None
    priority: Optional[int] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[List[V1Hook]] = None
    params: Optional[Dict[str, V1Param]] = None
    run_patch: Optional[Dict[str, Any]] = None
    patch_strategy: Optional[str] = None
    is_preset: Optional[bool] = None
    is_approved: Optional[bool] = None
    matrix: Optional[Any] = None
    joins: Optional[List[V1Join]] = None
    schedule: Optional[Any] = None
    dependencies: Optional[List[str]] = None
    trigger: Optional[str] = None  # all_succeeded|all_failed|all_done|one_succeeded|...
    conditions: Optional[str] = None
    skip_on_upstream_skip: Optional[bool] = None
    # Component source: exactly one of these.
    component: Optional[V1Component] = None
    hub_ref: Optional[str] = None
    dag_ref: Optional[str] = None
    url_ref: Optional[str] = None
    path_ref: Optional[str] = None

    @field_validator("kind")
    @classmethod
    def _kind(cls, v):
        if v != "operation":
            raise ValueError(f"Expected kind 'operation', got {v!r}")
        return v

    @field_validator("params", mode="before")
    @classmethod
    def _params(cls, v):
        return params_from_dict(v) if v is not None else None

    @field_validator("matrix", mode="before")
    @classmethod
    def _matrix(cls, v):
        return parse_matrix(v)

    @field_validator("schedule", mode="before")
    @classmethod
    def _schedule(cls, v):
        return parse_schedule(v)

    @model_validator(mode="after")
    def _one_component_source(self):
        sources = [
            s for s in (self.component, self.hub_ref, self.dag_ref,
                        self.url_ref, self.path_ref)
            if s is not None
        ]
        if len(sources) > 1:
            raise ValueError(
                "Operation must reference exactly one component source "
                "(component | hubRef | dagRef | urlRef | pathRef)"
            )
        return self

    @property
    def has_component(self) -> bool:
        return self.component is not None

    @property
    def effective_queue(self) -> Optional[str]:
        """None-aware op-over-component merge (the resolver's `pick`)."""
        if self.queue is not None:
            return self.queue
        return self.component.queue if self.has_component else None

    @property
    def effective_priority(self) -> int:
        # `is not None`, not truthiness: an explicit `priority: 0` on
        # the operation must override a component's nonzero priority.
        if self.priority is not None:
            return self.priority
        if self.has_component and self.component.priority is not None:
            return self.component.priority
        return 0


class V1CompiledOperation(BaseSchema):
    """Operation after resolution: component inlined, params validated,
    run patched, matrix/schedule carried for the scheduler."""

    version: Optional[float] = None
    kind: str = "compiled_operation"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[List[str]] = None
    presets: Optional[List[str]] = None
    queue: Optional[str] = None
    priority: Optional[int] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[List[V1Hook]] = None
    params: Optional[Dict[str, V1Param]] = None
    matrix: Optional[Any] = None
    joins: Optional[List[V1Join]] = None
    schedule: Optional[Any] = None
    dependencies: Optional[List[str]] = None
    trigger: Optional[str] = None
    conditions: Optional[str] = None
    skip_on_upstream_skip: Optional[bool] = None
    inputs: Optional[List[V1IO]] = None
    outputs: Optional[List[V1IO]] = None
    run: Optional[Any] = None

    @field_validator("kind")
    @classmethod
    def _kind(cls, v):
        if v != "compiled_operation":
            raise ValueError(f"Expected kind 'compiled_operation', got {v!r}")
        return v

    @field_validator("params", mode="before")
    @classmethod
    def _params(cls, v):
        return params_from_dict(v) if v is not None else None

    @field_validator("matrix", mode="before")
    @classmethod
    def _matrix(cls, v):
        return parse_matrix(v)

    @field_validator("schedule", mode="before")
    @classmethod
    def _schedule(cls, v):
        return parse_schedule(v)

    @field_validator("run", mode="before")
    @classmethod
    def _run(cls, v):
        return parse_runtime(v)

    @property
    def run_kind(self) -> Optional[str]:
        return getattr(self.run, "kind", None)

    @property
    def is_distributed(self) -> bool:
        return self.run_kind in RunKind.DISTRIBUTED

    @property
    def has_pipeline(self) -> bool:
        return self.matrix is not None or self.run_kind == RunKind.DAG or \
            self.schedule is not None

    def get_io_dict(self) -> Dict[str, Any]:
        """Resolved input values by name (for contexts/tracking)."""
        out = {}
        for io in self.inputs or []:
            out[io.name] = io.value
        return out
