"""Runtime kinds: what a run *is* once scheduled.

Parity with the reference's runtime union (SURVEY.md 2.4/2.5; expected at
``polyaxon/_flow/run/`` — unverified):

- ``V1Job``      — batch workload.
- ``V1Service``  — long-running endpoint (notebook/TensorBoard/REST).
- ``V1Dag``      — graph of operations with dependencies.
- ``V1TPUJob``   — **our native distributed kind**: replicated workload on a
  TPU slice topology, the TPU-first replacement for the reference's
  delegated Kubeflow kinds.
- ``V1TFJob`` / ``V1PytorchJob`` / ``V1MPIJob`` — compatibility kinds with
  the reference's replica vocabulary (chief/worker/ps, master/worker,
  launcher/worker), normalized onto TPU replica topology so existing
  polyaxonfiles run unchanged on TPU (BASELINE configs 2/3/5).
- ``V1PaddleJob`` / ``V1XGBoostJob`` / ``V1RayJob`` / ``V1DaskJob`` /
  ``V1MXNetJob`` —
  later-version reference kinds (SURVEY 2.5 long tail), same
  normalization: primary role (master/head/scheduler) is process 0.
- ``V1TunerJob`` / ``V1NotifierJob`` / ``V1CleanerJob`` — auxiliary kinds.

Scheduling-time kinds (``V1Schedule*``) say *when* runs materialize.
"""

from __future__ import annotations

from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import Field, field_validator

from .base import BaseSchema
from .environment import V1Environment, V1Init
from .k8s_refs import V1Container


class RunKind:
    JOB = "job"
    SERVICE = "service"
    DAG = "dag"
    TPUJOB = "tpujob"
    TFJOB = "tfjob"
    PYTORCHJOB = "pytorchjob"
    MPIJOB = "mpijob"
    PADDLEJOB = "paddlejob"
    XGBOOSTJOB = "xgboostjob"
    RAYJOB = "rayjob"
    DASKJOB = "daskjob"
    MXNETJOB = "mxnetjob"
    TUNER = "tuner"
    NOTIFIER = "notifier"
    CLEANER = "cleaner"

    DISTRIBUTED = {TPUJOB, TFJOB, PYTORCHJOB, MPIJOB,
                   PADDLEJOB, XGBOOSTJOB, RAYJOB, DASKJOB, MXNETJOB}


class V1Job(BaseSchema):
    kind: Literal["job"] = "job"
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    volumes: Optional[List[Dict[str, Any]]] = None
    init: Optional[List[V1Init]] = None
    sidecars: Optional[List[V1Container]] = None
    container: Optional[V1Container] = None


class V1Service(BaseSchema):
    kind: Literal["service"] = "service"
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    volumes: Optional[List[Dict[str, Any]]] = None
    init: Optional[List[V1Init]] = None
    sidecars: Optional[List[V1Container]] = None
    container: Optional[V1Container] = None
    ports: Optional[List[int]] = None
    replicas: Optional[int] = None
    is_external: Optional[bool] = None
    rewrite_path: Optional[bool] = None


# ---------------------------------------------------------------------------
# Distributed kinds
# ---------------------------------------------------------------------------

class V1TPUReplica(BaseSchema):
    """One replica role of a TPU job (parity: reference ``V1KFReplica``).

    On TPU a replica is one *host process* of a slice: ``replicas`` hosts,
    each seeing the chips its topology grants.  The runtime derives
    ``jax.distributed`` process ids from the replica index env the agent
    injects (SURVEY.md 3.2/5.8).
    """

    replicas: Optional[int] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    volumes: Optional[List[Dict[str, Any]]] = None
    init: Optional[List[V1Init]] = None
    sidecars: Optional[List[V1Container]] = None
    container: Optional[V1Container] = None


class V1SliceSpec(BaseSchema):
    """TPU slice request: accelerator type + topology.

    Examples: ``type="v5litepod-16", topology="4x4"`` (16 chips, 4 hosts).
    ``num_slices > 1`` enables multi-slice jobs: ICI within a slice, DCN
    across slices — the mesh axes the parallel runtime builds on.
    """

    type: str = "v5litepod-8"
    topology: Optional[str] = None
    num_slices: int = 1
    chips_per_host: int = 4
    megascale: Optional[bool] = None

    @property
    def chips_per_slice(self) -> int:
        if self.topology:
            dims = [int(d) for d in self.topology.lower().split("x")]
            n = 1
            for d in dims:
                n *= d
            return n
        # v5litepod-8 -> 8 chips etc.
        tail = self.type.rsplit("-", 1)
        if len(tail) == 2 and tail[1].isdigit():
            return int(tail[1])
        raise ValueError(f"Cannot infer chip count from slice type {self.type!r}")

    @property
    def hosts_per_slice(self) -> int:
        return max(1, self.chips_per_slice // self.chips_per_host)

    @property
    def total_chips(self) -> int:
        return self.chips_per_slice * self.num_slices


class V1TPUJob(BaseSchema):
    """Native TPU distributed kind (replaces delegated TFJob/PytorchJob/MPIJob).

    ``coordinator`` is replica 0 of ``worker`` unless a dedicated
    coordinator replica is given; its stable DNS name seeds
    ``jax.distributed.initialize``.
    """

    kind: Literal["tpujob"] = "tpujob"
    slice: Optional[V1SliceSpec] = Field(default=None)
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    coordinator: Optional[V1TPUReplica] = None
    worker: Optional[V1TPUReplica] = None
    strategy: Optional[Dict[str, Any]] = None  # dp/tp/pp/sp/ep axis sizes

    def get_replica_roles(self) -> Dict[str, V1TPUReplica]:
        roles = {}
        if self.coordinator:
            roles["coordinator"] = self.coordinator
        if self.worker:
            roles["worker"] = self.worker
        return roles


class V1KFReplica(BaseSchema):
    """Replica spec compatible with the reference's Kubeflow vocabulary."""

    replicas: Optional[int] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    volumes: Optional[List[Dict[str, Any]]] = None
    init: Optional[List[V1Init]] = None
    sidecars: Optional[List[V1Container]] = None
    container: Optional[V1Container] = None


class V1TFJob(BaseSchema):
    """Compatibility kind: reference ``V1TFJob`` (chief/worker/ps/evaluator).

    The compiler maps chief+worker onto TPU worker processes; ps/evaluator
    roles are rejected on TPU (parameter servers have no ICI analogue) with
    a clear error unless replicas == 0.
    """

    kind: Literal["tfjob"] = "tfjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    chief: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    ps: Optional[V1KFReplica] = None
    evaluator: Optional[V1KFReplica] = None


class V1PytorchJob(BaseSchema):
    """Compatibility kind: reference ``V1PytorchJob`` (master/worker, DDP).

    DDP-over-NCCL becomes DP with XLA AllReduce over ICI."""

    kind: Literal["pytorchjob"] = "pytorchjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    n_proc_per_node: Optional[int] = None


class V1MPIJob(BaseSchema):
    """Compatibility kind: reference ``V1MPIJob`` (launcher/worker, Horovod).

    Horovod ring-allreduce becomes XLA AllReduce on the ICI torus (the
    hardware *is* the ring)."""

    kind: Literal["mpijob"] = "mpijob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    slots_per_worker: Optional[int] = None
    launcher: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1PaddleJob(BaseSchema):
    """Compatibility kind: reference ``V1PaddleJob`` (master/worker).

    Paddle's fleet collectives become XLA AllReduce over ICI."""

    kind: Literal["paddlejob"] = "paddlejob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1XGBoostJob(BaseSchema):
    """Compatibility kind: reference ``V1XGBoostJob`` (master/worker).

    Rabit allreduce becomes XLA AllReduce; trees build data-parallel."""

    kind: Literal["xgboostjob"] = "xgboostjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1RayJob(BaseSchema):
    """Compatibility kind: reference ``V1RayJob`` (head + worker groups,
    entrypoint/rayVersion/runtimeEnv metadata).

    The head role maps to process 0 (the jax.distributed coordinator);
    named worker groups each become a replica group; Ray's object-store
    data paths have no TPU analogue — replicas run the SPMD program.
    ``entrypoint``/``ray_version``/``runtime_env`` are accepted for
    polyaxonfile compatibility (the container command is the program)."""

    kind: Literal["rayjob"] = "rayjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    entrypoint: Optional[str] = None
    ray_version: Optional[str] = None
    runtime_env: Optional[Dict[str, Any]] = None
    metadata: Optional[Dict[str, Any]] = None
    head: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    workers: Optional[Dict[str, V1KFReplica]] = None  # named groups


class V1DaskJob(BaseSchema):
    """Compatibility kind: reference ``V1DaskJob`` (job/scheduler/worker).

    The scheduler role maps to process 0; job + workers join the one
    SPMD gang."""

    kind: Literal["daskjob"] = "daskjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    job: Optional[V1KFReplica] = None
    scheduler: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1MXNetJob(BaseSchema):
    """Compatibility kind: reference ``V1MXJob`` (scheduler/server/worker,
    SURVEY 2.5 long tail).

    MXNet's KVStore topology collapses like tfjob's: ``server``
    (parameter-server) replicas have no TPU analogue — gradients ride
    XLA AllReduce on ICI — so the normalizer rejects them; the
    ``scheduler`` maps to process 0 and workers join the SPMD gang.
    ``tuner``/``tuner_tracker``/``tuner_server`` are accepted for
    polyaxonfile compatibility (auto-tuning is the tuner subsystem's
    job here) but take no processes."""

    kind: Literal["mxnetjob"] = "mxnetjob"
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[Dict[str, Any]] = None
    slice: Optional[V1SliceSpec] = None
    scheduler: Optional[V1KFReplica] = None
    server: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    tuner: Optional[V1KFReplica] = None
    tuner_tracker: Optional[V1KFReplica] = None
    tuner_server: Optional[V1KFReplica] = None


# ---------------------------------------------------------------------------
# DAG
# ---------------------------------------------------------------------------

class V1Dag(BaseSchema):
    """A graph of operations; edges from explicit dependencies + param refs."""

    kind: Literal["dag"] = "dag"
    operations: Optional[List[Any]] = None  # List[V1Operation]; late-bound
    components: Optional[List[Any]] = None  # List[V1Component]; late-bound
    concurrency: Optional[int] = None
    early_stopping: Optional[List[Any]] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    volumes: Optional[List[Dict[str, Any]]] = None


# ---------------------------------------------------------------------------
# Auxiliary kinds
# ---------------------------------------------------------------------------

class V1TunerJob(BaseSchema):
    kind: Literal["tuner"] = "tuner"
    container: Optional[V1Container] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None
    init: Optional[List[V1Init]] = None


class V1NotifierJob(BaseSchema):
    kind: Literal["notifier"] = "notifier"
    container: Optional[V1Container] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None


class V1CleanerJob(BaseSchema):
    kind: Literal["cleaner"] = "cleaner"
    container: Optional[V1Container] = None
    environment: Optional[V1Environment] = None
    connections: Optional[List[str]] = None


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------

class V1CronSchedule(BaseSchema):
    kind: Literal["cron"] = "cron"
    cron: str
    start_at: Optional[str] = None
    end_at: Optional[str] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None


class V1IntervalSchedule(BaseSchema):
    kind: Literal["interval"] = "interval"
    frequency: Union[int, float]
    start_at: Optional[str] = None
    end_at: Optional[str] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None


class V1DateTimeSchedule(BaseSchema):
    kind: Literal["datetime"] = "datetime"
    start_at: str


V1Schedule = Union[V1CronSchedule, V1IntervalSchedule, V1DateTimeSchedule]

V1Runtime = Union[
    V1Job,
    V1Service,
    V1Dag,
    V1TPUJob,
    V1TFJob,
    V1PytorchJob,
    V1MPIJob,
    V1PaddleJob,
    V1XGBoostJob,
    V1RayJob,
    V1DaskJob,
    V1MXNetJob,
    V1TunerJob,
    V1NotifierJob,
    V1CleanerJob,
]

RUNTIME_BY_KIND = {
    RunKind.JOB: V1Job,
    RunKind.SERVICE: V1Service,
    RunKind.DAG: V1Dag,
    RunKind.TPUJOB: V1TPUJob,
    RunKind.TFJOB: V1TFJob,
    RunKind.PYTORCHJOB: V1PytorchJob,
    RunKind.MPIJOB: V1MPIJob,
    RunKind.PADDLEJOB: V1PaddleJob,
    RunKind.XGBOOSTJOB: V1XGBoostJob,
    RunKind.RAYJOB: V1RayJob,
    RunKind.DASKJOB: V1DaskJob,
    RunKind.MXNETJOB: V1MXNetJob,
    RunKind.TUNER: V1TunerJob,
    RunKind.NOTIFIER: V1NotifierJob,
    RunKind.CLEANER: V1CleanerJob,
}

SCHEDULE_BY_KIND = {
    "cron": V1CronSchedule,
    "interval": V1IntervalSchedule,
    "datetime": V1DateTimeSchedule,
}


def parse_runtime(data: Union[Dict[str, Any], V1Runtime, None]):
    if data is None or not isinstance(data, dict):
        return data
    kind = data.get("kind")
    cls = RUNTIME_BY_KIND.get(kind)
    if cls is None:
        raise ValueError(
            f"Unknown run kind {kind!r}; expected one of {sorted(RUNTIME_BY_KIND)}"
        )
    return cls.from_dict(data)


def parse_schedule(data: Union[Dict[str, Any], None]):
    if data is None or not isinstance(data, dict):
        return data
    kind = data.get("kind")
    cls = SCHEDULE_BY_KIND.get(kind)
    if cls is None:
        raise ValueError(
            f"Unknown schedule kind {kind!r}; expected one of {sorted(SCHEDULE_BY_KIND)}"
        )
    return cls.from_dict(data)
