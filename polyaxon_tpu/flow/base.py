"""Base model plumbing for all flow schemas.

The reference's polyflow schemas (SURVEY.md section 2.3, expected at
``polyaxon/_flow/`` in the reference tree — unavailable/unverified) are
pydantic-style models with camelCase YAML fields.  We use pydantic v2 with
a camelCase alias generator so YAML written for the reference parses here.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type, TypeVar

from pydantic import BaseModel, ConfigDict

T = TypeVar("T", bound="BaseSchema")


def to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


class BaseSchema(BaseModel):
    """Base for every V1* schema: camelCase aliases, permissive extras off."""

    model_config = ConfigDict(
        alias_generator=to_camel,
        populate_by_name=True,
        extra="forbid",
        validate_assignment=True,
        protected_namespaces=(),
    )

    @classmethod
    def from_dict(cls: Type[T], data: Dict[str, Any]) -> T:
        return cls.model_validate(data)

    def to_dict(self, exclude_none: bool = True) -> Dict[str, Any]:
        return self.model_dump(by_alias=True, exclude_none=exclude_none)

    def to_json(self, exclude_none: bool = True) -> str:
        return self.model_dump_json(by_alias=True, exclude_none=exclude_none)

    def clone(self: T) -> T:
        return self.model_copy(deep=True)


class BaseOpenSchema(BaseSchema):
    """Schema that tolerates unknown fields (forward compatibility)."""

    model_config = ConfigDict(
        alias_generator=to_camel,
        populate_by_name=True,
        extra="allow",
        protected_namespaces=(),
    )


def patch_dict(base: Optional[Dict[str, Any]], patch: Optional[Dict[str, Any]],
               strategy: str = "post_merge") -> Optional[Dict[str, Any]]:
    """Recursive dict merge used by presets/patches.

    Strategies (mirroring the reference's patch semantics, SURVEY.md 2.2):
      - post_merge: patch wins on conflicts (deep merge).
      - pre_merge:  base wins on conflicts (deep merge).
      - replace:    patch replaces base wholesale.
      - isnull:     patch fills only keys absent/None in base.
    """
    if base is None:
        return patch if patch is None else dict(patch)
    if patch is None:
        return dict(base)
    if strategy == "replace":
        return dict(patch)

    out: Dict[str, Any] = dict(base)
    for key, pval in patch.items():
        bval = out.get(key)
        if isinstance(bval, dict) and isinstance(pval, dict):
            out[key] = patch_dict(bval, pval, strategy)
        elif strategy == "post_merge":
            out[key] = pval
        elif strategy == "pre_merge":
            if key not in out:
                out[key] = pval
        elif strategy == "isnull":
            if bval is None:
                out[key] = pval
        else:
            raise ValueError(f"Unknown patch strategy: {strategy}")
    return out
