"""Minimal template engine for ``{{ expr }}`` resolution.

The reference uses Jinja-style templating inside specs.  We implement the
subset the capability surface needs — dotted lookups, bare IO names, and a
few filters — with no external dependency:

    {{ lr }}                      -> inputs.lr
    {{ globals.run_outputs_path }}
    {{ matrix.lr }}
    {{ params.batch | int }}
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Union

from ..flow.io import TEMPLATE_RE

_FILTERS = {
    "int": int,
    "float": float,
    "str": str,
    "bool": bool,
    "json": lambda v: json.dumps(v),
    "upper": lambda v: str(v).upper(),
    "lower": lambda v: str(v).lower(),
    "basename": lambda v: str(v).rsplit("/", 1)[-1],
}


class TemplateError(ValueError):
    pass


def _lookup(path: str, ctx: Dict[str, Any]) -> Any:
    cur: Any = ctx
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                raise TemplateError(f"Unknown context path: {path!r}")
            cur = cur[part]
        elif isinstance(cur, (list, tuple)) and part.lstrip("-").isdigit():
            try:
                cur = cur[int(part)]
            except IndexError:
                raise TemplateError(
                    f"Index {part} out of range in context path {path!r} "
                    f"(length {len(cur)})"
                )
        else:
            attr = getattr(cur, part, _MISSING)
            if attr is _MISSING:
                raise TemplateError(f"Unknown context path: {path!r}")
            cur = attr
    return cur


_MISSING = object()


def _eval_expr(expr: str, ctx: Dict[str, Any]) -> Any:
    parts = [p.strip() for p in expr.split("|")]
    value = _lookup(parts[0], ctx)
    for filt in parts[1:]:
        fn = _FILTERS.get(filt)
        if fn is None:
            raise TemplateError(f"Unknown template filter: {filt!r}")
        value = fn(value)
    return value


def resolve_str(text: str, ctx: Dict[str, Any]) -> Any:
    """Resolve templates in one string.

    A string that is exactly one template returns the native value
    (so ``{{ epochs }}`` can stay an int); otherwise values are
    interpolated into the surrounding text.
    """
    match = TEMPLATE_RE.fullmatch(text.strip())
    if match:
        return _eval_expr(match.group(1), ctx)

    def sub(m: "re.Match[str]") -> str:
        v = _eval_expr(m.group(1), ctx)
        return json.dumps(v) if isinstance(v, (dict, list)) else str(v)

    return TEMPLATE_RE.sub(sub, text)


def resolve_obj(obj: Any, ctx: Dict[str, Any]) -> Any:
    """Recursively resolve templates in nested dicts/lists/strings."""
    if isinstance(obj, str):
        return resolve_str(obj, ctx) if "{{" in obj else obj
    if isinstance(obj, dict):
        return {k: resolve_obj(v, ctx) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [resolve_obj(v, ctx) for v in obj]
    return obj


def has_template(obj: Any) -> bool:
    if isinstance(obj, str):
        return "{{" in obj
    if isinstance(obj, dict):
        return any(has_template(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(has_template(v) for v in obj)
    return False
