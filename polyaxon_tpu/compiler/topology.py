"""Normalize distributed run kinds onto TPU process topology.

The reference delegates distributed topology to Kubeflow CRs per kind
(TFJob chief/worker/ps, PytorchJob master/worker, MPIJob launcher/worker —
SURVEY.md 2.5).  On TPU every kind collapses to the same shape: N host
processes over one or more slices, process 0 doubling as the
``jax.distributed`` coordinator.  This module computes that normal form;
the k8s converter, the agent's env injection, and the runtime bootstrap
all consume it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..flow.run import (
    RunKind,
    V1MPIJob,
    V1SliceSpec,
    V1TFJob,
    V1TPUJob,
)


class TopologyError(ValueError):
    pass


# Compat kinds that collapse to "primary role (process 0, the
# jax.distributed coordinator) + secondary roles in one SPMD gang"
# (SURVEY 2.5): kind -> (primary role, secondary roles in order).
_COMPAT_ROLES = {
    RunKind.PYTORCHJOB: ("master", ("worker",)),
    RunKind.PADDLEJOB: ("master", ("worker",)),
    RunKind.XGBOOSTJOB: ("master", ("worker",)),
    RunKind.RAYJOB: ("head", ("worker",)),
    RunKind.DASKJOB: ("scheduler", ("job", "worker")),
    RunKind.MXNETJOB: ("scheduler", ("worker",)),
}

# Roles with no TPU analogue, per kind: parameter-server topologies
# dissolve into XLA collectives.
_COMPAT_REJECT = {
    RunKind.TFJOB: ("ps", "evaluator"),
    RunKind.MXNETJOB: ("server",),
}


def _reject_roles(run: Any, kind: str) -> None:
    for bad in _COMPAT_REJECT.get(kind, ()):
        rep = getattr(run, bad, None)
        if rep is not None and _nonzero(rep) > 0:
            raise TopologyError(
                f"{kind} role {bad!r} has no TPU analogue (parameter "
                "servers are not used with XLA collectives); set its "
                "replicas to 0 or use collective training")


@dataclass
class ReplicaGroup:
    """One role of the job (e.g. worker) with its process count."""

    role: str
    replicas: int
    spec: Any  # V1TPUReplica | V1KFReplica


@dataclass
class ProcessTopology:
    """The normal form every distributed kind maps to."""

    kind: str
    slice: V1SliceSpec
    groups: List[ReplicaGroup] = field(default_factory=list)

    @property
    def num_processes(self) -> int:
        return sum(g.replicas for g in self.groups)

    @property
    def coordinator_role(self) -> str:
        return self.groups[0].role if self.groups else "worker"

    def coordinator_address(self, service_fmt: str = "{run}-{role}-{index}",
                            run: str = "run", port: int = 8476) -> str:
        """Stable DNS of process 0 — seeds jax.distributed.initialize."""
        role = self.coordinator_role
        return f"{service_fmt.format(run=run, role=role, index=0)}:{port}"

    def process_env(self, role: str, index: int, run: str = "run",
                    port: int = 8476,
                    service_fmt: str = "{run}-{role}-{index}",
                    ) -> Dict[str, str]:
        """Env block injected per pod so in-container bootstrap can derive
        (coordinator, num_processes, process_id) — SURVEY.md 3.2/5.8.

        ``service_fmt`` must yield a resolvable DNS name; in-cluster the
        converter passes a pod-hostname.headless-subdomain format."""
        offset = 0
        for g in self.groups:
            if g.role == role:
                if not 0 <= index < g.replicas:
                    raise TopologyError(
                        f"Replica index {index} out of range for role "
                        f"{role!r} with {g.replicas} replicas"
                    )
                break
            offset += g.replicas
        else:
            raise TopologyError(f"Unknown role {role!r}")
        return {
            "PTPU_COORDINATOR_ADDRESS": self.coordinator_address(
                service_fmt=service_fmt, run=run, port=port),
            "PTPU_NUM_PROCESSES": str(self.num_processes),
            "PTPU_PROCESS_ID": str(offset + index),
            "PTPU_REPLICA_ROLE": role,
            "PTPU_REPLICA_INDEX": str(index),
            "PTPU_SLICE_TYPE": self.slice.type,
            "PTPU_SLICE_TOPOLOGY": self.slice.topology or "",
            "PTPU_NUM_SLICES": str(self.slice.num_slices),
            "PTPU_CHIPS_PER_HOST": str(self.slice.chips_per_host),
        }


def _nonzero(replica) -> int:
    if replica is None:
        return 0
    return replica.replicas if replica.replicas is not None else 1


def normalize(run: Any) -> ProcessTopology:
    """Map any distributed run kind to ProcessTopology."""
    kind = getattr(run, "kind", None)
    slice_spec = getattr(run, "slice", None) or V1SliceSpec()

    if isinstance(run, V1TPUJob) or kind == RunKind.TPUJOB:
        groups = []
        if run.coordinator and _nonzero(run.coordinator):
            groups.append(ReplicaGroup("coordinator", _nonzero(run.coordinator),
                                       run.coordinator))
        if run.worker and _nonzero(run.worker):
            groups.append(ReplicaGroup("worker", _nonzero(run.worker), run.worker))
        if not groups:
            raise TopologyError("tpujob needs at least one replica group")
        return ProcessTopology(kind=RunKind.TPUJOB, slice=slice_spec, groups=groups)

    if isinstance(run, V1TFJob) or kind == RunKind.TFJOB:
        _reject_roles(run, RunKind.TFJOB)
        groups = []
        if run.chief and _nonzero(run.chief):
            groups.append(ReplicaGroup("chief", _nonzero(run.chief), run.chief))
        if run.worker and _nonzero(run.worker):
            groups.append(ReplicaGroup("worker", _nonzero(run.worker), run.worker))
        if not groups:
            raise TopologyError("tfjob needs chief and/or worker replicas")
        return ProcessTopology(kind=RunKind.TFJOB, slice=slice_spec, groups=groups)

    if isinstance(run, V1MPIJob) or kind == RunKind.MPIJOB:
        # The MPI launcher does not participate in collectives; on TPU the
        # coordinator is worker 0, so the launcher role dissolves.
        groups = []
        if run.worker and _nonzero(run.worker):
            groups.append(ReplicaGroup("worker", _nonzero(run.worker), run.worker))
        if not groups:
            raise TopologyError("mpijob needs worker replicas")
        return ProcessTopology(kind=RunKind.MPIJOB, slice=slice_spec, groups=groups)

    if kind in _COMPAT_ROLES:
        _reject_roles(run, kind)
        primary_role, secondary_roles = _COMPAT_ROLES[kind]
        groups = []
        for role in (primary_role,) + tuple(secondary_roles):
            rep = getattr(run, role, None)
            if rep is not None and _nonzero(rep):
                groups.append(ReplicaGroup(role, _nonzero(rep), rep))
        # rayjob: named worker groups (the reference's `workers` dict);
        # insertion order defines their process-id offsets.  Group names
        # become pod hostnames / DNS labels and must be unique roles —
        # a duplicate would collapse two groups into one replicaSpec
        # while the process count still counts both (a gang that never
        # fully assembles).
        seen_roles = {g.role for g in groups}
        for group_name, rep in (getattr(run, "workers", None) or {}).items():
            if rep is None or not _nonzero(rep):
                continue
            # The name is a FRAGMENT of the pod hostname
            # ("<run-uuid>-<role>-<index>", assembled by the converter):
            # budget 63-char DNS label minus 12-char uuid, two dashes,
            # and up to 4 index digits -> 45 chars for the role.
            if not re.fullmatch(r"[a-z0-9]([-a-z0-9]{0,43}[a-z0-9])?",
                                group_name):
                raise TopologyError(
                    f"worker group name {group_name!r} is not a valid "
                    "pod-hostname fragment (lowercase alphanumerics and "
                    "'-', max 45 chars: the 63-char DNS label budget "
                    "minus the run-uuid prefix and replica index)")
            if group_name in seen_roles:
                raise TopologyError(
                    f"worker group name {group_name!r} collides with "
                    "another replica role")
            seen_roles.add(group_name)
            groups.append(ReplicaGroup(group_name, _nonzero(rep), rep))
        if not groups:
            raise TopologyError(
                f"{kind} needs {primary_role} and/or worker replicas")
        return ProcessTopology(kind=kind, slice=slice_spec, groups=groups)

    raise TopologyError(f"Run kind {kind!r} is not a distributed kind")
