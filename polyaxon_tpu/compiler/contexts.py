"""Context building for template resolution.

Parity with the reference's compiler contexts (SURVEY.md 2.6): a resolved
operation exposes ``globals.*`` (run identity and canonical paths),
``inputs``/``outputs`` by name, and — for matrix children — ``matrix.*``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


def run_artifacts_path(run_uuid: str, root: Optional[str] = None) -> str:
    """Canonical artifacts dir for a run.

    Must agree with ``client.store.FileRunStore.artifacts_path`` — the
    templated ``{{ globals.run_artifacts_path }}`` a job writes to is the
    same tree the store, lineage, and tuner joins read from.  ``root``
    overrides the home dir (e.g. a mounted artifacts store in-cluster).
    """
    from ..client.store import default_home

    home = root or default_home()
    return os.path.join(home, "runs", run_uuid, "artifacts")


def run_outputs_path(run_uuid: str, root: Optional[str] = None) -> str:
    return os.path.join(run_artifacts_path(run_uuid, root), "outputs")


def build_globals(
    run_uuid: str,
    run_name: Optional[str] = None,
    project: Optional[str] = None,
    iteration: Optional[int] = None,
    created_at: Optional[str] = None,
    store_path: Optional[str] = None,
) -> Dict[str, Any]:
    from ..client.store import default_home

    artifacts = run_artifacts_path(run_uuid, store_path)
    return {
        "run_uuid": run_uuid,
        "uuid": run_uuid,
        "run_name": run_name or run_uuid,
        "name": run_name or run_uuid,
        "project_name": project or "default",
        "project_uuid": project or "default",
        "iteration": iteration,
        "created_at": created_at,
        "run_artifacts_path": artifacts,
        "run_outputs_path": os.path.join(artifacts, "outputs"),
        "artifacts_path": artifacts,
        "outputs_path": os.path.join(artifacts, "outputs"),
        "store_path": store_path or default_home(),
        "namespace": os.environ.get("POLYAXON_TPU_NAMESPACE", "polyaxon-tpu"),
    }


# Namespaces bare IO names must never shadow.
RESERVED_CONTEXT_KEYS = frozenset(
    {"globals", "inputs", "outputs", "params", "matrix", "dag", "connections"}
)


def build_contexts(
    globals_ctx: Dict[str, Any],
    inputs: Optional[Dict[str, Any]] = None,
    outputs: Optional[Dict[str, Any]] = None,
    matrix: Optional[Dict[str, Any]] = None,
    connections: Optional[Dict[str, Any]] = None,
    dag: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    ctx: Dict[str, Any] = {
        "globals": dict(globals_ctx),
        "inputs": dict(inputs or {}),
        "outputs": dict(outputs or {}),
        "params": {**(inputs or {}), **(outputs or {})},
        "connections": dict(connections or {}),
    }
    if matrix:
        ctx["matrix"] = dict(matrix)
    if dag:
        ctx["dag"] = dict(dag)
    # IO names are addressable bare ({{ lr }}) like the reference, but may
    # never shadow the reserved namespaces above.
    for name, value in {**(inputs or {}), **(outputs or {})}.items():
        if name not in RESERVED_CONTEXT_KEYS:
            ctx.setdefault(name, value)
    return ctx
