"""Operation resolution: V1Operation -> V1CompiledOperation.

Parity with the reference's compiler pipeline (SURVEY.md 2.6, call stack
3.1 step 4): validate params against the component IO contract, resolve
references and ``{{ ... }}`` templates against contexts, apply run patches,
and inline everything into a self-contained compiled operation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..flow import V1CompiledOperation, V1Component, V1Operation
from ..flow.base import patch_dict
from ..flow.io import V1IO, V1Param
from .contexts import RESERVED_CONTEXT_KEYS, build_contexts, build_globals
from .templates import TemplateError, has_template, resolve_obj


class CompilerError(ValueError):
    pass


RefResolver = Callable[[str, str], Any]
"""(ref, key) -> value; resolves runs.<uuid>/ops.<name> output references."""


def make_compiled(operation: V1Operation) -> V1CompiledOperation:
    """Inline component into the operation (no resolution yet)."""
    if not operation.has_component:
        raise CompilerError(
            "Operation has no inline component; hub/path refs must be "
            "materialized before compilation"
        )
    comp: V1Component = operation.component
    run = comp.run
    if run is None:
        raise CompilerError(f"Component {comp.name!r} declares no run section")

    run_dict = run.to_dict()
    if operation.run_patch:
        run_dict = patch_dict(run_dict, operation.run_patch,
                              operation.patch_strategy or "post_merge")

    def pick(op_val, comp_val):
        return op_val if op_val is not None else comp_val

    return V1CompiledOperation.from_dict(
        {
            "kind": "compiled_operation",
            "version": pick(operation.version, comp.version),
            "name": operation.name or comp.name,
            "description": pick(operation.description, comp.description),
            "tags": sorted(set(operation.tags or []) | set(comp.tags or [])) or None,
            "presets": operation.presets,
            "queue": pick(operation.queue, comp.queue),
            "priority": pick(operation.priority, comp.priority),
            "cache": pick(operation.cache, comp.cache),
            "termination": pick(
                operation.termination.to_dict() if operation.termination else None,
                comp.termination.to_dict() if comp.termination else None,
            ),
            "plugins": pick(
                operation.plugins.to_dict() if operation.plugins else None,
                comp.plugins.to_dict() if comp.plugins else None,
            ),
            "build": pick(
                operation.build.to_dict() if operation.build else None,
                comp.build.to_dict() if comp.build else None,
            ),
            "hooks": [h.to_dict() for h in (operation.hooks or comp.hooks or [])] or None,
            "params": {k: p.to_dict() for k, p in (operation.params or {}).items()} or None,
            "matrix": operation.matrix.to_dict() if operation.matrix else None,
            "joins": [j.to_dict()
                      for j in (operation.joins
                                or getattr(comp, "joins", None)
                                or [])] or None,
            "schedule": operation.schedule.to_dict() if operation.schedule else None,
            "dependencies": operation.dependencies,
            "trigger": operation.trigger,
            "conditions": operation.conditions,
            "skip_on_upstream_skip": operation.skip_on_upstream_skip,
            "inputs": [io.to_dict() for io in (comp.inputs or [])] or None,
            "outputs": [io.to_dict() for io in (comp.outputs or [])] or None,
            "run": run_dict,
        }
    )


def resolve_params(
    compiled: V1CompiledOperation,
    matrix_values: Optional[Dict[str, Any]] = None,
    ref_resolver: Optional[RefResolver] = None,
    join_values: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Materialize param values into the compiled op's inputs.

    Returns the resolved {name: value} dict.  ``matrix_values`` supplies
    ``{{ matrix.* }}`` / ref="matrix" params for sweep children;
    ``ref_resolver`` resolves runs./ops. references (wired to the store or
    DAG state by the scheduler).
    """
    from ..flow.io import check_declared_params, fill_default_params

    declared: Dict[str, V1IO] = {io.name: io for io in (compiled.inputs or [])}
    out_names = {io.name for io in (compiled.outputs or [])}
    owner = f"operation {compiled.name!r}"
    resolved: Dict[str, Any] = {}

    for name, param in (compiled.params or {}).items():
        if param.context_only:
            continue
        value = param.value
        if param.ref is not None:
            if param.ref == "matrix":
                if matrix_values is None or value not in matrix_values:
                    raise CompilerError(
                        f"Param {name!r} references matrix.{value} but no "
                        "matrix value was provided"
                    )
                value = matrix_values[value]
            elif param.ref in ("dag", "globals"):
                # Left template-shaped; resolved against contexts below.
                value = f"{{{{ {param.ref}.{value} }}}}"
            else:  # runs.<uuid> | ops.<name>
                if ref_resolver is None:
                    raise CompilerError(
                        f"Param {name!r} references {param.ref!r} but no "
                        "ref resolver is available in this compilation pass"
                    )
                value = ref_resolver(param.ref, str(value))
        resolved[name] = value

    try:
        check_declared_params(resolved, declared, out_names, owner)
    except ValueError as e:
        raise CompilerError(str(e)) from e

    # Matrix/join params flow in even without explicit ref= entries.
    for name, value in (matrix_values or {}).items():
        resolved.setdefault(name, value)
    for name, value in (join_values or {}).items():
        resolved.setdefault(name, value)

    try:
        fill_default_params(declared, resolved, owner)
    except ValueError as e:
        raise CompilerError(str(e)) from e
    return resolved


def resolve(
    operation: V1Operation,
    run_uuid: str,
    run_name: Optional[str] = None,
    project: Optional[str] = None,
    iteration: Optional[int] = None,
    matrix_values: Optional[Dict[str, Any]] = None,
    ref_resolver: Optional[RefResolver] = None,
    store_path: Optional[str] = None,
    dag_values: Optional[Dict[str, Any]] = None,
    join_values: Optional[Dict[str, Any]] = None,
) -> V1CompiledOperation:
    """Full resolution: compile, materialize params, resolve templates.

    ``dag_values`` supplies the ``{{ dag.* }}`` context (upstream op
    outputs) when this op runs inside a DAG; ``join_values`` the
    query-joined param lists (``runner.joins``).
    """
    compiled = make_compiled(operation)

    resolved = resolve_params(compiled, matrix_values=matrix_values,
                              ref_resolver=ref_resolver,
                              join_values=join_values)

    globals_ctx = build_globals(
        run_uuid=run_uuid, run_name=run_name or compiled.name,
        project=project, iteration=iteration, store_path=store_path,
    )
    ctx = build_contexts(globals_ctx, inputs=resolved, matrix=matrix_values,
                         dag=dag_values)

    # Resolve templates inside param values themselves (e.g. paths built
    # from globals or from other params).  Params may chain (a param whose
    # template names another templated param), so iterate to a fixpoint.
    declared = {io.name: io for io in (compiled.inputs or [])}
    for _ in range(len(resolved) + 1):
        progressed = False
        for name, value in list(resolved.items()):
            if not has_template(value):
                continue
            try:
                new_value = resolve_obj(value, ctx)
            except TemplateError:
                continue  # may depend on a not-yet-resolved param
            if has_template(new_value) and new_value == value:
                continue
            resolved[name] = new_value
            ctx["inputs"][name] = new_value
            ctx["params"][name] = new_value
            if name not in RESERVED_CONTEXT_KEYS:
                ctx[name] = new_value
            progressed = True
        if not progressed:
            break
    unresolvable = {n: v for n, v in resolved.items() if has_template(v)}
    if unresolvable:
        # Surface the real lookup error when there is one; otherwise the
        # template is circular/self-referential — fail explicitly rather
        # than shipping literal '{{ ... }}' text into the container.
        for name, value in unresolvable.items():
            try:
                resolve_obj(value, ctx)
            except TemplateError as e:
                raise CompilerError(
                    f"Param {name!r} cannot be resolved: {e}"
                ) from e
        raise CompilerError(
            f"Circular or self-referential param templates: "
            f"{sorted(unresolvable)}"
        )

    for name, value in list(resolved.items()):
        io = declared.get(name)
        if io is not None:
            value = io.validate_value(value)
        resolved[name] = value
        ctx["inputs"][name] = value
        ctx["params"][name] = value
        if name not in RESERVED_CONTEXT_KEYS:
            ctx[name] = value

    # Write resolved values onto the IO declarations.
    new_inputs = []
    for io in compiled.inputs or []:
        io = io.clone()
        if io.name in resolved:
            io.value = resolved[io.name]
        new_inputs.append(io)
    compiled.inputs = new_inputs or None

    # Resolve templates throughout the run section.  Dag member operations
    # keep their templates: each member resolves against its OWN run
    # context when the DagRunner executes it.
    run_dict = compiled.run.to_dict()
    if compiled.run_kind == "dag":
        member_ops = run_dict.pop("operations", None)
        member_comps = run_dict.pop("components", None)
        run_dict = resolve_obj(run_dict, ctx)
        if member_ops is not None:
            run_dict["operations"] = member_ops
        if member_comps is not None:
            run_dict["components"] = member_comps
    else:
        run_dict = resolve_obj(run_dict, ctx)
    compiled.run = run_dict  # validator re-parses into the proper kind

    return compiled
