"""Compiler: operation -> compiled operation with resolved params/contexts."""

from .contexts import build_contexts, build_globals, run_artifacts_path, run_outputs_path
from .resolver import CompilerError, make_compiled, resolve, resolve_params
from .templates import TemplateError, has_template, resolve_obj, resolve_str
from .topology import ProcessTopology, ReplicaGroup, TopologyError, normalize
