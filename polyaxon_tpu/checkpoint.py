"""Checkpoint/resume: Orbax-backed run checkpointing (SURVEY.md 5.4).

The reference is framework-agnostic — user code must load its own
checkpoints from the outputs store, and ``ops restart/resume`` just
point a new run at the prior artifacts.  Here checkpointing is a
first-class runtime service, TPU-style:

- **async saves off the step path** (Orbax background thread) so the
  training loop never blocks on HBM->host->store transfers;
- sharding-aware restore: arrays come back with the live mesh's
  shardings (pass ``abstract_state``/the current state template);
- ``restore_or_init`` = the auto-resume hook the runner wires when a
  run is restarted/resumed: latest step wins, empty store -> fresh;
- preemption-friendly: ``save(..., force=True)`` on SIGTERM via
  ``install_preemption_hook`` so TPU-slice reclaims lose at most the
  in-flight step (GKE sends SIGTERM ahead of reclaim).

Layout: ``<run outputs>/checkpoints/<step>/`` — visible to the sidecar
sync, the lineage plane, and ``ops restart --copy``.
"""

from __future__ import annotations

import logging
import os
import signal
from typing import Any, Optional

logger = logging.getLogger(__name__)

CHECKPOINTS_DIR = "checkpoints"


class CheckpointManager:
    """Thin, typed wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(
        self,
        directory: Optional[str] = None,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
        run_uuid: Optional[str] = None,
    ):
        import orbax.checkpoint as ocp

        if directory is None:
            directory = default_checkpoint_dir(run_uuid)
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._ocp = ocp
        self._manager = ocp.CheckpointManager(self.directory,
                                              options=options)
        # Set by the SIGTERM hook when an immediate save is impossible
        # (state donated into an in-flight step); the training loop
        # polls it and saves cooperatively.
        self.preempt_requested = False

    # -- save/restore ----------------------------------------------------

    def save(self, step: int, state: Any, *, force: bool = False,
             metrics: Optional[dict] = None) -> bool:
        """Queue an (async) save; returns whether a save was started.
        Idempotent: re-saving an existing step is a no-op, not an error
        (final forced saves often land on the last periodic step)."""
        try:
            saved = self._manager.save(
                int(step),
                args=self._ocp.args.StandardSave(state),
                metrics=metrics,
                force=force,
            )
        except self._ocp.checkpoint_manager.StepAlreadyExistsError:
            return False
        return bool(saved)

    def restore(self, step: Optional[int] = None,
                template: Any = None) -> Any:
        """Restore a step (default: latest).  ``template`` carries the
        target structure/shardings (the freshly-initialized state)."""
        step = int(step) if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoints under {self.directory}")
        if template is not None:
            import jax

            # to_shape_dtype_struct preserves each template leaf's
            # sharding (and special-cases PRNG key arrays), so the
            # restored arrays land directly on the train-step's layout
            # — PROVIDED the template is committed to its shardings
            # (see TrainStep.init_state's step counter).
            abstract = jax.tree.map(
                self._ocp.utils.to_shape_dtype_struct, template)
            return self._manager.restore(
                step, args=self._ocp.args.StandardRestore(abstract))
        return self._manager.restore(step)

    def restore_or_init(self, init_state: Any) -> tuple:
        """(state, restored_step): auto-resume or fresh start."""
        step = self.latest_step()
        if step is None:
            return init_state, None
        logger.info("resuming from checkpoint step %s", step)
        return self.restore(step, template=init_state), step

    # -- introspection ---------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self):
        return sorted(self._manager.all_steps())

    def wait(self) -> None:
        """Block until queued async saves are durable."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()

    # -- preemption ------------------------------------------------------

    def install_preemption_hook(self, get_state, get_step) -> None:
        """SIGTERM -> forced save (TPU reclaim notice).

        ``get_state``/``get_step`` are callables so the hook always saves
        the *current* state, not the one at install time.

        With buffer donation the signal can land in the window where the
        bound state was already donated into an in-flight step (its
        arrays are deleted).  The handler then CANNOT save immediately —
        and it cannot wait either, since the new state is only bound
        once the handler returns.  It sets ``preempt_requested`` instead
        and returns; the training loop checks the flag after each step,
        saves the fresh (undonated) output state, and exits within the
        operator's SIGTERM grace period.
        """
        prev = signal.getsignal(signal.SIGTERM)

        def terminate(signum):
            if callable(prev):
                prev(signum, None)
            else:
                # SIG_DFL/SIG_IGN are not callable: restore and
                # re-raise so the process actually terminates
                # (otherwise graceful stops hang until SIGKILL).
                signal.signal(signum, prev or signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        def handler(signum, frame):
            logger.warning("preemption notice: forcing checkpoint")
            try:
                self.save(int(get_step()), get_state(), force=True)
                self.wait()
            except Exception:
                # Donated/deleted buffers (or a mid-save failure): defer
                # to the cooperative path in the training loop.
                logger.warning(
                    "immediate preemption save failed (state donated "
                    "into an in-flight step?); deferring to the loop")
                self.preempt_requested = True
                return
            terminate(signum)

        self.preempt_requested = False
        signal.signal(signal.SIGTERM, handler)


def default_checkpoint_dir(run_uuid: Optional[str] = None) -> str:
    """``<run outputs>/checkpoints`` for the active (or given) run."""
    from .compiler.contexts import run_outputs_path

    run_uuid = run_uuid or os.environ.get("POLYAXON_TPU_RUN_UUID")
    if run_uuid:
        return os.path.join(run_outputs_path(run_uuid), CHECKPOINTS_DIR)
    return os.path.join(os.getcwd(), CHECKPOINTS_DIR)
