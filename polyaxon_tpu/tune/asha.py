"""ASHA — Asynchronous Successive Halving (Li et al. 2020).

The reference's sweep algorithms (SURVEY.md 2.11) top out at Hyperband,
whose rungs are BARRIERS: every config in a rung must finish before any
promotion happens, so straggler trials idle the whole worker pool.  On
a TPU-slice fleet stragglers are the norm (preemptions, queue delays),
so the tuner adds ASHA: promotion decisions are made the moment a
worker frees up —

- rung k trains with resource ``r_k = R * eta^(k - max_rung)`` — the
  top rung at exactly ``max_iterations`` (R), descending by eta down
  to a bottom rung that still gets at least ``min_resource``;
- a free worker first tries to PROMOTE: scanning rungs top-down, any
  completed trial that sits in the top ``floor(|rung| / eta)`` of its
  rung and hasn't been promoted yet advances to rung k+1 immediately;
- otherwise it STARTS a fresh config at rung 0 (until ``num_runs``
  configs have been sampled);
- when neither applies it waits for in-flight trials (a straggler's
  completion can unlock promotions).

No barriers anywhere: one slow trial delays only its own promotion,
never the pool.  The synchronous counterpart lives in hyperband.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .space import sample_params


@dataclass
class _Entry:
    config_id: int
    params: Dict[str, Any]
    metric: Optional[float] = None
    promoted: bool = False


@dataclass
class AshaJob:
    config_id: int
    rung: int
    resource: float
    params: Dict[str, Any]


class ASHAManager:
    """Bookkeeping for one ASHA run.  Thread-compatible but NOT
    thread-safe — the controller serializes next_job()/report() under
    its own lock (the decisions must be atomic with respect to each
    other anyway)."""

    def __init__(self, config):
        self.config = config
        self.eta = float(config.eta)
        if self.eta <= 1:
            raise ValueError("asha eta must be > 1")
        self.R = float(config.max_iterations)
        self.r0 = float(config.min_resource)
        if self.r0 <= 0 or self.r0 > self.R:
            raise ValueError(
                f"min_resource must be in (0, max_iterations]; got "
                f"{self.r0} vs R={self.R}")
        # Rungs are anchored DOWNWARD from R (like hyperband's
        # bracket_r): the top rung trains at exactly max_iterations,
        # rung k at R * eta^(k - max_rung), with max_rung the largest
        # depth whose bottom rung still gets >= min_resource.  An
        # upward r0*eta^k ladder would strand up to an eta-factor of
        # the user's budget (R=100, eta=3: top rung 81, never 100).
        self.max_rung = int(math.floor(
            math.log(self.R / self.r0) / math.log(self.eta) + 1e-9))
        if config.resource.cast(
                self.R * self.eta ** (-self.max_rung)) <= 0:
            # int resource + fractional min_resource can truncate the
            # bottom rung to 0 — children would "train" for zero
            # epochs yet still compete for promotion.
            raise ValueError(
                f"min_resource={self.r0} with resource type "
                f"{config.resource.type!r} yields a rung-0 resource of "
                f"0 after casting; raise min_resource so the bottom "
                f"rung trains at >= 1")
        self.num_runs = int(config.num_runs)
        self.rng = np.random.default_rng(config.seed)
        # rung index -> completed entries (in completion order)
        self.rungs: Dict[int, List[_Entry]] = {
            k: [] for k in range(self.max_rung + 1)}
        self._started = 0
        self._next_config_id = 0

    # ------------------------------------------------------------------

    def resource_at(self, rung: int) -> float:
        r = self.R * self.eta ** (rung - self.max_rung)
        return self.config.resource.cast(r)

    def _is_better(self, a: float, b: float) -> bool:
        return self.config.metric.is_better(a, b)

    def _promotable(self, rung: int) -> Optional[_Entry]:
        """Best unpromoted entry inside rung's top floor(n/eta), if
        any.  The top set GROWS as completions arrive — that is the
        asynchrony: early completions promote before the rung 'fills'
        (there is no notion of full).  NaN metrics (diverged trials)
        are excluded like failures: Python's sort leaves NaN wherever
        it lands (all comparisons False), which would let a diverged
        config win every promotion."""
        entries = [e for e in self.rungs[rung]
                   if e.metric is not None
                   and not math.isnan(e.metric)]
        k = int(math.floor(len(entries) / self.eta))
        if k <= 0:
            return None
        ordered = sorted(entries, key=lambda e: e.metric,
                         reverse=self.config.metric.optimization
                         == "maximize")
        for e in ordered[:k]:
            if not e.promoted:
                return e
        return None

    def next_job(self) -> Optional[AshaJob]:
        """Promotion first (top rung down — deeper trials are worth
        more compute), else a fresh rung-0 config, else None (caller
        waits on in-flight trials or finishes)."""
        for rung in range(self.max_rung - 1, -1, -1):
            e = self._promotable(rung)
            if e is not None:
                e.promoted = True
                return AshaJob(config_id=e.config_id, rung=rung + 1,
                               resource=self.resource_at(rung + 1),
                               params=dict(e.params))
        if self._started < self.num_runs:
            self._started += 1
            cid = self._next_config_id
            self._next_config_id += 1
            params = sample_params(self.config.params, self.rng)
            return AshaJob(config_id=cid, rung=0,
                           resource=self.resource_at(0), params=params)
        return None

    def report(self, job: AshaJob, metric: Optional[float]) -> None:
        """Record a completed trial.  ``metric=None`` (failed child)
        still lands in the rung so the sweep terminates, but it can
        never promote."""
        self.rungs[job.rung].append(_Entry(
            config_id=job.config_id, params=job.params, metric=metric))

    # ------------------------------------------------------------------

    def counts(self) -> Dict[int, int]:
        return {k: len(v) for k, v in self.rungs.items()}

    def best(self) -> Optional[Tuple[Dict[str, Any], float]]:
        top: Optional[_Entry] = None
        for entries in self.rungs.values():
            for e in entries:
                if e.metric is None or math.isnan(e.metric):
                    continue
                if top is None or self._is_better(e.metric, top.metric):
                    top = e
        return None if top is None else (dict(top.params), top.metric)
