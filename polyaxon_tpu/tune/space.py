"""Search-space sampling/enumeration over the V1Hp* distribution schemas.

Parity: the reference's per-algorithm suggestion managers share this
vocabulary (SURVEY.md 2.11).  All randomness flows through a seeded
``numpy.random.Generator`` so suggestion tests are deterministic.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..flow.matrix import DISCRETE_KINDS


class SpaceError(ValueError):
    pass


def enumerate_hp(hp: Any) -> List[Any]:
    """All values of a discrete distribution (grid expansion)."""
    kind = getattr(hp, "kind", None)
    if kind is None:
        return [hp]  # literal
    if kind == "choice":
        return list(hp.value)
    if kind == "range":
        start, stop, step = hp.as_tuple()
        vals = list(np.arange(start, stop, step))
        return [v.item() if hasattr(v, "item") else v for v in vals]
    if kind == "linspace":
        start, stop, num = hp.as_tuple()
        return [v.item() for v in np.linspace(start, stop, num)]
    if kind == "logspace":
        start, stop, num = hp.as_tuple()
        return [v.item() for v in np.logspace(start, stop, num)]
    if kind == "geomspace":
        start, stop, num = hp.as_tuple()
        return [v.item() for v in np.geomspace(start, stop, num)]
    raise SpaceError(
        f"Distribution {kind!r} is continuous; it cannot be enumerated "
        f"(grid supports {sorted(DISCRETE_KINDS)})"
    )


def sample_hp(hp: Any, rng: np.random.Generator) -> Any:
    """One random draw from any distribution."""
    kind = getattr(hp, "kind", None)
    if kind is None:
        return hp
    if kind == "choice":
        return hp.value[int(rng.integers(len(hp.value)))]
    if kind == "pchoice":
        options = [pair[0] for pair in hp.value]
        probs = [float(pair[1]) for pair in hp.value]
        return options[int(rng.choice(len(options), p=probs))]
    if kind in DISCRETE_KINDS:
        values = enumerate_hp(hp)
        return values[int(rng.integers(len(values)))]
    if kind == "uniform":
        low, high = hp.as_tuple()
        return float(rng.uniform(low, high))
    if kind == "quniform":
        low, high = hp.as_tuple()
        return round(float(rng.uniform(low, high)))
    if kind == "loguniform":
        low, high = hp.as_tuple()
        if low <= 0 or high <= 0:
            raise SpaceError("loguniform bounds must be > 0")
        return float(np.exp(rng.uniform(math.log(low), math.log(high))))
    if kind == "qloguniform":
        low, high = hp.as_tuple()
        return round(float(np.exp(rng.uniform(math.log(low), math.log(high)))))
    if kind == "normal":
        loc, scale = hp.as_tuple()
        return float(rng.normal(loc, scale))
    if kind == "qnormal":
        loc, scale = hp.as_tuple()
        return round(float(rng.normal(loc, scale)))
    if kind == "lognormal":
        loc, scale = hp.as_tuple()
        return float(rng.lognormal(loc, scale))
    if kind == "qlognormal":
        loc, scale = hp.as_tuple()
        return round(float(rng.lognormal(loc, scale)))
    raise SpaceError(f"Unknown distribution kind {kind!r}")


def sample_params(params: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
    return {name: sample_hp(hp, rng) for name, hp in params.items()}


def grid_params(params: Dict[str, Any],
                num_runs: Optional[int] = None) -> List[Dict[str, Any]]:
    """Cartesian product of all discrete axes."""
    import itertools

    names = list(params)
    axes = [enumerate_hp(params[n]) for n in names]
    combos = itertools.product(*axes)
    out = [dict(zip(names, combo)) for combo in combos]
    if num_runs is not None:
        out = out[:num_runs]
    return out


def to_unit(hp: Any, value: Any) -> float:
    """Map a value into [0,1] for surrogate models (bayes/TPE)."""
    kind = getattr(hp, "kind", None)
    if kind in ("choice", "pchoice"):
        options = (hp.value if kind == "choice"
                   else [p[0] for p in hp.value])
        return options.index(value) / max(1, len(options) - 1)
    if kind in ("uniform", "quniform"):
        low, high = hp.as_tuple()
        return (float(value) - low) / max(1e-12, high - low)
    if kind in ("loguniform", "qloguniform"):
        low, high = hp.as_tuple()
        return ((math.log(float(value)) - math.log(low))
                / max(1e-12, math.log(high) - math.log(low)))
    if kind in ("normal", "qnormal"):
        loc, scale = hp.as_tuple()
        return 0.5 + 0.5 * math.erf((float(value) - loc) / (scale * math.sqrt(2)))
    if kind in ("lognormal", "qlognormal"):
        loc, scale = hp.as_tuple()
        return 0.5 + 0.5 * math.erf((math.log(max(float(value), 1e-300)) - loc)
                                    / (scale * math.sqrt(2)))
    if kind in DISCRETE_KINDS:
        values = enumerate_hp(hp)
        return values.index(value) / max(1, len(values) - 1)
    raise SpaceError(f"Cannot normalize kind {kind!r}")


def from_unit(hp: Any, unit: float) -> Any:
    """Inverse of to_unit (approximate for q*/discrete kinds)."""
    unit = min(1.0, max(0.0, unit))
    kind = getattr(hp, "kind", None)
    if kind in ("choice", "pchoice"):
        options = (hp.value if kind == "choice"
                   else [p[0] for p in hp.value])
        return options[int(round(unit * (len(options) - 1)))]
    if kind in ("uniform", "quniform"):
        low, high = hp.as_tuple()
        v = low + unit * (high - low)
        return round(v) if kind == "quniform" else float(v)
    if kind in ("loguniform", "qloguniform"):
        low, high = hp.as_tuple()
        v = math.exp(math.log(low) + unit * (math.log(high) - math.log(low)))
        return round(v) if kind == "qloguniform" else float(v)
    if kind in DISCRETE_KINDS:
        values = enumerate_hp(hp)
        return values[int(round(unit * (len(values) - 1)))]
    if kind in ("normal", "qnormal", "lognormal", "qlognormal"):
        from statistics import NormalDist

        loc, scale = hp.as_tuple()
        unit = min(1.0 - 1e-9, max(1e-9, unit))
        z = NormalDist(loc, scale).inv_cdf(unit)
        if kind == "normal":
            return float(z)
        if kind == "qnormal":
            return round(z)
        v = math.exp(z)
        return round(v) if kind == "qlognormal" else float(v)
    raise SpaceError(f"Cannot denormalize kind {kind!r}")
