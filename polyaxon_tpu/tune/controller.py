"""Tuner controller: drives matrix operations as pipelines of child runs.

Parity: reference call stack 3.3 (SURVEY.md) — the controller computes
suggestion batches, creates child operations (bounded by ``concurrency``),
joins on tracked metrics from the store, applies early stopping, promotes
(hyperband) or re-suggests (bayes/TPE), and aggregates the final status +
best result onto the pipeline run.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Any, Dict, List, Optional

import numpy as np

from ..flow import V1Operation
from ..flow.matrix import (
    V1Asha,
    V1Bayes,
    V1FailureEarlyStopping,
    V1GridSearch,
    V1Hyperband,
    V1Hyperopt,
    V1Iterative,
    V1Mapping,
    V1MetricEarlyStopping,
    V1RandomSearch,
)
from ..lifecycle import V1Statuses
from .asha import ASHAManager
from .bayes import BayesManager
from .hyperband import HyperbandManager
from .space import grid_params, sample_params
from .tpe import TPEManager


class TuneError(RuntimeError):
    pass


class TuneController:
    def __init__(self, executor, operation: V1Operation, pipeline_uuid: str):
        if operation.matrix is None:
            raise TuneError("Operation has no matrix")
        self.executor = executor
        self.store = executor.store
        self.operation = operation
        self.matrix = operation.matrix
        self.pipeline_uuid = pipeline_uuid
        self.concurrency = getattr(self.matrix, "concurrency", None) or 4
        self.results: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._stopped_by_user = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------------

    def _metric_name(self) -> Optional[str]:
        metric = getattr(self.matrix, "metric", None)
        return metric.name if metric else None

    def _child_operation(self, index: int) -> V1Operation:
        name = self.operation.name or "tune"
        return self.operation.model_copy(update={
            "matrix": None,
            "schedule": None,
            "name": f"{name}-{index}",
        })

    def _run_child(self, index: int, params: Dict[str, Any],
                   extra_meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Execute one suggestion; returns {'params', 'metric', 'status', 'uuid'}."""
        self._poll_pipeline_stop()
        if self._stop.is_set():
            out = {"params": params, "metric": None, "metrics": {},
                   "status": V1Statuses.SKIPPED, "uuid": None}
            with self._lock:
                self.results.append(out)
            return out
        op = self._child_operation(index)
        try:
            record = self.executor.run_operation(
                op, matrix_values=params, pipeline=self.pipeline_uuid)
            uuid = record["uuid"]
            if extra_meta:
                self.store.update_run(uuid, meta_info=extra_meta)
            metrics = self.store.last_metrics(uuid)
            metric_name = self._metric_name()
            metric = metrics.get(metric_name) if metric_name else None
            out = {"params": params, "metric": metric, "metrics": metrics,
                   "status": record["status"], "uuid": uuid}
        except Exception as e:  # child failure must not kill the sweep
            out = {"params": params, "metric": None, "metrics": {},
                   "status": V1Statuses.FAILED, "uuid": None,
                   "error": str(e)}
        with self._lock:
            self.results.append(out)
            self._check_early_stopping()
        return out

    def _poll_pipeline_stop(self) -> None:
        """Honor `ops stop <pipeline-uuid>`: stop launching trials."""
        try:
            status = self.store.get_run(self.pipeline_uuid).get("status")
        except Exception:
            return
        if status in (V1Statuses.STOPPING, V1Statuses.STOPPED):
            self._stopped_by_user = True
            self._stop.set()

    def _check_early_stopping(self) -> None:
        for policy in getattr(self.matrix, "early_stopping", None) or []:
            if isinstance(policy, V1MetricEarlyStopping):
                for r in self.results:
                    # The policy names its own metric series — it need not
                    # be the sweep's optimization metric.
                    v = (r.get("metrics") or {}).get(policy.metric)
                    if v is None:
                        continue
                    hit = (v >= policy.value
                           if policy.optimization == "maximize"
                           else v <= policy.value)
                    if hit:
                        self._stop.set()
                        return
            elif isinstance(policy, V1FailureEarlyStopping):
                done = [r for r in self.results]
                if done:
                    failed = sum(1 for r in done
                                 if r["status"] == V1Statuses.FAILED)
                    if 100.0 * failed / len(done) >= policy.percent:
                        self._stop.set()
                        return

    def _run_batch(self, suggestions: List[Dict[str, Any]],
                   start_index: int,
                   extra_meta: Optional[Dict[str, Any]] = None
                   ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            futures = {
                pool.submit(self._run_child, start_index + i, params,
                            extra_meta): i
                for i, params in enumerate(suggestions)
            }
            for fut in as_completed(futures):
                out.append(fut.result())
        return out

    # ------------------------------------------------------------------

    def execute(self) -> Dict[str, Any]:
        self.store.set_status(self.pipeline_uuid, V1Statuses.RUNNING,
                              reason="TuneController", force=True)
        try:
            matrix = self.matrix
            if isinstance(matrix, V1Mapping):
                self._run_batch(list(matrix.values), 0)
            elif isinstance(matrix, V1GridSearch):
                self._run_batch(grid_params(matrix.params, matrix.num_runs), 0)
            elif isinstance(matrix, V1RandomSearch):
                rng = np.random.default_rng(matrix.seed)
                suggestions = [sample_params(matrix.params, rng)
                               for _ in range(matrix.num_runs)]
                self._run_batch(suggestions, 0)
            elif isinstance(matrix, V1Hyperband):
                self._run_hyperband(matrix)
            elif isinstance(matrix, V1Asha):
                self._run_asha(matrix)
            elif isinstance(matrix, V1Bayes):
                self._run_bayes(matrix)
            elif isinstance(matrix, V1Hyperopt):
                self._run_hyperopt(matrix)
            elif isinstance(matrix, V1Iterative):
                self._run_iterative(matrix)
            else:
                raise TuneError(f"Unsupported matrix kind: {matrix.kind}")
        except Exception as e:
            self.store.set_status(self.pipeline_uuid, V1Statuses.FAILED,
                                  reason="TuneController", message=str(e),
                                  force=True)
            raise

        return self._finalize()

    # -- per-algorithm drivers -------------------------------------------

    def _run_hyperband(self, matrix: V1Hyperband) -> None:
        mgr = HyperbandManager(matrix)
        index = 0
        for s in mgr.brackets():
            if self._stop.is_set():
                break
            rungs = mgr.rungs(s)
            population = mgr.initial_suggestions(s)
            for rung in rungs:
                if self._stop.is_set():
                    break
                population = population[:rung.n_configs]
                resource_value = mgr.resource_value(rung)
                suggestions = [
                    {**params, matrix.resource.name: resource_value}
                    for params in population
                ]
                batch = self._run_batch(
                    suggestions, index,
                    extra_meta={"bracket": s, "rung": rung.rung},
                )
                index += len(batch)
                keep = mgr.promote_count(s, rung.rung)
                if keep <= 0:
                    break
                top = mgr.select_top(batch, keep)
                population = [
                    {k: v for k, v in r["params"].items()
                     if k != matrix.resource.name}
                    for r in top
                ]
                if not population:
                    break

    def _run_asha(self, matrix: V1Asha) -> None:
        """Barrier-free worker pool: each free worker asks the manager
        for a promotion or a fresh config the moment it idles; a worker
        with nothing to do waits on the condition because a straggler's
        completion can unlock promotions.  Contrast _run_hyperband,
        whose rungs are batch barriers."""
        mgr = ASHAManager(matrix)
        cond = threading.Condition()
        state = {"inflight": 0, "index": 0}

        def worker():
            while True:
                with cond:
                    if self._stop.is_set():
                        cond.notify_all()
                        return
                    job = mgr.next_job()
                    while job is None and state["inflight"] > 0 \
                            and not self._stop.is_set():
                        cond.wait(timeout=0.5)
                        job = mgr.next_job()
                    if job is None or self._stop.is_set():
                        cond.notify_all()
                        return
                    state["inflight"] += 1
                    idx = state["index"]
                    state["index"] += 1
                # finally-guarded: an exception escaping _run_child
                # (e.g. early-stopping policy math on a bad metric
                # value) must still decrement inflight, or every other
                # worker waits on the condition forever.
                out = None
                try:
                    params = {**job.params,
                              matrix.resource.name: job.resource}
                    out = self._run_child(idx, params, extra_meta={
                        "rung": job.rung, "config_id": job.config_id})
                finally:
                    with cond:
                        ok = out is not None and \
                            out["status"] == V1Statuses.SUCCEEDED
                        mgr.report(job,
                                   out.get("metric") if ok else None)
                        state["inflight"] -= 1
                        cond.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _run_bayes(self, matrix: V1Bayes) -> None:
        mgr = BayesManager(matrix)
        self._run_batch(mgr.initial_suggestions(), 0)
        for i in range(matrix.max_iterations):
            if self._stop.is_set():
                break
            with self._lock:
                observations = list(self.results)
            params = mgr.suggest(observations)
            self._run_batch([params], len(self.results))

    def _run_hyperopt(self, matrix: V1Hyperopt) -> None:
        mgr = TPEManager(matrix)
        n_initial = min(4, matrix.num_runs)
        rng = np.random.default_rng(matrix.seed)
        self._run_batch([sample_params(matrix.params, rng)
                         for _ in range(n_initial)], 0)
        for i in range(matrix.num_runs - n_initial):
            if self._stop.is_set():
                break
            with self._lock:
                observations = list(self.results)
            self._run_batch([mgr.suggest(observations)], len(self.results))

    def _run_iterative(self, matrix: V1Iterative) -> None:
        rng = np.random.default_rng(matrix.seed)
        for i in range(matrix.max_iterations):
            if self._stop.is_set():
                break
            self._run_batch([sample_params(matrix.params, rng)],
                            len(self.results))

    # ------------------------------------------------------------------

    def _finalize(self) -> Dict[str, Any]:
        metric_name = self._metric_name()
        succeeded = [r for r in self.results
                     if r["status"] == V1Statuses.SUCCEEDED]
        outputs: Dict[str, Any] = {
            "num_trials": len(self.results),
            "num_succeeded": len(succeeded),
            "num_failed": sum(1 for r in self.results
                              if r["status"] == V1Statuses.FAILED),
        }
        if metric_name:
            metric = getattr(self.matrix, "metric")
            scored = [r for r in self.results if r.get("metric") is not None]
            if scored:
                best = (max if metric.optimization == "maximize" else min)(
                    scored, key=lambda r: r["metric"])
                outputs["best_metric"] = best["metric"]
                outputs["best_params"] = best["params"]
                outputs["best_run"] = best["uuid"]
        self.store.update_run(self.pipeline_uuid, outputs=outputs)
        if self._stopped_by_user:
            status = V1Statuses.STOPPED
        elif succeeded:
            status = V1Statuses.SUCCEEDED
        else:
            status = V1Statuses.FAILED
        self.store.set_status(self.pipeline_uuid, status,
                              reason="TuneController", force=True)
        return self.store.get_run(self.pipeline_uuid)
