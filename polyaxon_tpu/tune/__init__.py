"""Hyperparameter tuning (the polytune-equivalent).

Native implementations (no hyperopt/skopt dependency): grid & mapping
expansion, seeded random search, Hyperband bracket/rung successive halving,
GP-based Bayesian optimization, TPE (hyperopt-style), iterative sampling —
all driven by ``TuneController`` which creates child runs through an
executor and joins on tracked metrics (SURVEY.md 2.11, call stack 3.3).
"""

from .bayes import BayesManager, GaussianProcess
from .controller import TuneController, TuneError
from .asha import AshaJob, ASHAManager
from .hyperband import HyperbandManager, Rung
from .space import (
    SpaceError,
    enumerate_hp,
    from_unit,
    grid_params,
    sample_hp,
    sample_params,
    to_unit,
)
from .tpe import TPEManager
