"""Hyperband: successive-halving brackets (Li et al. 2018).

Parity with the reference's bracket/rung math (SURVEY.md 2.11/3.3 —
``hypertune`` hyperband manager, unverified path).  Given ``max_iterations``
(R) and ``eta``:

    s_max = floor(log_eta(R));  B = (s_max + 1) * R

Bracket s in [s_max, ..., 0]:
    n_s = ceil(B/R * eta^s / (s+1))   initial configs
    r_s = R * eta^-s                  initial resource
Rung i in [0..s]:
    n_i = floor(n_s * eta^-i)        configs surviving into rung i
    r_i = r_s * eta^i                resource for rung i
Top n_{i+1} by metric advance to the next rung.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..flow.matrix import V1Hyperband
from .space import sample_params


@dataclass
class Rung:
    bracket: int
    rung: int
    n_configs: int
    resource: float


class HyperbandManager:
    def __init__(self, config: V1Hyperband):
        self.config = config
        self.eta = float(config.eta)
        self.max_iterations = int(config.max_iterations)
        if self.eta <= 1:
            raise ValueError("hyperband eta must be > 1")
        self.s_max = int(math.floor(
            math.log(self.max_iterations) / math.log(self.eta)))
        self.B = (self.s_max + 1) * self.max_iterations
        self.rng = np.random.default_rng(config.seed)

    # -- static math ------------------------------------------------------

    def brackets(self) -> List[int]:
        return list(range(self.s_max, -1, -1))

    def bracket_n(self, s: int) -> int:
        return int(math.ceil(
            (self.B / self.max_iterations) * (self.eta ** s) / (s + 1)))

    def bracket_r(self, s: int) -> float:
        return self.max_iterations * (self.eta ** (-s))

    def rungs(self, s: int) -> List[Rung]:
        n, r = self.bracket_n(s), self.bracket_r(s)
        out = []
        for i in range(s + 1):
            out.append(Rung(
                bracket=s, rung=i,
                n_configs=int(math.floor(n * self.eta ** (-i))),
                resource=r * (self.eta ** i),
            ))
        return out

    def promote_count(self, s: int, rung_i: int) -> int:
        """How many configs advance out of rung i of bracket s."""
        rungs = self.rungs(s)
        if rung_i + 1 >= len(rungs):
            return 0
        return rungs[rung_i + 1].n_configs

    # -- suggestion flow --------------------------------------------------

    def initial_suggestions(self, s: int) -> List[Dict[str, Any]]:
        return [sample_params(self.config.params, self.rng)
                for _ in range(self.bracket_n(s))]

    def resource_value(self, rung: Rung):
        return self.config.resource.cast(rung.resource)

    def select_top(self, results: List[Dict[str, Any]], k: int) -> List[Dict[str, Any]]:
        """results: [{'params':..., 'metric': float}]; best-k by metric."""
        metric = self.config.metric
        scored = [r for r in results if r.get("metric") is not None]
        reverse = metric.optimization == "maximize"
        scored.sort(key=lambda r: r["metric"], reverse=reverse)
        return scored[:k]
