"""Bayesian optimization with a native Gaussian-process surrogate.

Parity: the reference's bayes manager (SURVEY.md 2.11) wraps an external
optimizer; here the GP (RBF kernel + jitter, exact solve — trial counts are
tiny) and the acquisition (expected improvement / UCB / POI) are implemented
directly on numpy, with params mapped into the unit cube via
``space.to_unit``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..flow.matrix import V1Bayes
from .space import from_unit, sample_params, to_unit


class GaussianProcess:
    def __init__(self, length_scale: float = 0.2, noise: float = 1e-6):
        self.length_scale = length_scale
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.asarray(x, dtype=float)
        y = np.asarray(y, dtype=float)
        self._y_mean = y.mean()
        self._y_std = y.std() or 1.0
        self._y = (y - self._y_mean) / self._y_std
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self._y))

    def predict(self, x: np.ndarray):
        x = np.asarray(x, dtype=float)
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)


class BayesManager:
    def __init__(self, config: V1Bayes):
        self.config = config
        self.rng = np.random.default_rng(config.seed)
        self.names = list(config.params)
        utility = config.utility_function or {}
        self.acquisition = utility.get("acquisitionFunction",
                                       utility.get("acquisition_function", "ei"))
        self.kappa = float(utility.get("kappa", 2.576))
        self.eps = float(utility.get("eps", 1e-2))
        self.n_candidates = int(utility.get("numCandidates", 512))

    # ------------------------------------------------------------------

    def initial_suggestions(self) -> List[Dict[str, Any]]:
        return [sample_params(self.config.params, self.rng)
                for _ in range(self.config.num_initial_runs)]

    def _encode(self, params: Dict[str, Any]) -> List[float]:
        return [to_unit(self.config.params[n], params[n]) for n in self.names]

    def _decode(self, unit: np.ndarray) -> Dict[str, Any]:
        return {n: from_unit(self.config.params[n], float(u))
                for n, u in zip(self.names, unit)}

    def suggest(self, observations: List[Dict[str, Any]]) -> Dict[str, Any]:
        """observations: [{'params': {...}, 'metric': float}] -> next params."""
        obs = [o for o in observations if o.get("metric") is not None]
        if len(obs) < 2:
            return sample_params(self.config.params, self.rng)
        sign = 1.0 if self.config.metric.optimization == "maximize" else -1.0
        x = np.array([self._encode(o["params"]) for o in obs])
        y = sign * np.array([float(o["metric"]) for o in obs])

        gp = GaussianProcess()
        gp.fit(x, y)
        candidates = self.rng.uniform(0, 1, size=(self.n_candidates, len(self.names)))
        mean, std = gp.predict(candidates)
        best = y.max()

        if self.acquisition == "ucb":
            score = mean + self.kappa * std
        elif self.acquisition == "poi":
            score = _norm_cdf((mean - best - self.eps) / std)
        else:  # expected improvement
            z = (mean - best - self.eps) / std
            score = (mean - best - self.eps) * _norm_cdf(z) + std * _norm_pdf(z)
        return self._decode(candidates[int(np.argmax(score))])
