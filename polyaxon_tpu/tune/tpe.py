"""Tree-structured Parzen Estimator (hyperopt-style) implemented natively.

Parity: the reference's ``V1Hyperopt`` delegates to the hyperopt package
(SURVEY.md 2.11); here TPE runs on numpy: observations are split at the
gamma-quantile into good/bad sets, each modeled with a per-dimension
Gaussian KDE in unit space, and candidates maximize l(x)/g(x).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..flow.matrix import V1Hyperopt
from .space import from_unit, sample_params, to_unit


class TPEManager:
    def __init__(self, config: V1Hyperopt, gamma: float = 0.25,
                 n_candidates: int = 128):
        self.config = config
        self.rng = np.random.default_rng(config.seed)
        self.names = list(config.params)
        self.gamma = gamma
        self.n_candidates = n_candidates

    def _encode(self, params: Dict[str, Any]) -> List[float]:
        return [to_unit(self.config.params[n], params[n]) for n in self.names]

    def _decode(self, unit: np.ndarray) -> Dict[str, Any]:
        return {n: from_unit(self.config.params[n], float(u))
                for n, u in zip(self.names, unit)}

    @staticmethod
    def _kde_logpdf(points: np.ndarray, samples: np.ndarray,
                    bandwidth: float) -> np.ndarray:
        # points [c, d], samples [n, d] -> log density per candidate
        d2 = (points[:, None, :] - samples[None, :, :]) ** 2
        log_k = -0.5 * d2 / bandwidth ** 2
        per_dim = np.logaddexp.reduce(log_k, axis=1) - np.log(len(samples))
        return per_dim.sum(-1)

    def suggest(self, observations: List[Dict[str, Any]]) -> Dict[str, Any]:
        if self.config.algorithm == "rand":
            return sample_params(self.config.params, self.rng)
        obs = [o for o in observations if o.get("metric") is not None]
        if len(obs) < 4:
            return sample_params(self.config.params, self.rng)
        metric = self.config.metric
        sign = -1.0 if (metric and metric.optimization == "maximize") else 1.0
        x = np.array([self._encode(o["params"]) for o in obs])
        y = sign * np.array([float(o["metric"]) for o in obs])  # lower=better

        n_good = max(1, int(np.ceil(self.gamma * len(obs))))
        order = np.argsort(y)
        good, bad = x[order[:n_good]], x[order[n_good:]]
        bandwidth = max(0.05, 1.0 / max(2, len(obs)) ** 0.5)

        candidates = np.clip(
            good[self.rng.integers(len(good), size=self.n_candidates)]
            + self.rng.normal(0, bandwidth, size=(self.n_candidates,
                                                  len(self.names))),
            0.0, 1.0,
        )
        score = (self._kde_logpdf(candidates, good, bandwidth)
                 - self._kde_logpdf(candidates, bad, bandwidth))
        return self._decode(candidates[int(np.argmax(score))])
