"""CompiledOperation -> Kubernetes resources.

The reference's converter layer (SURVEY.md 2.10, L2): turns a compiled
operation into the ``Operation`` custom resource our operator reconciles.
Differences from the reference are exactly the north-star's asks:

- resources: ``google.com/tpu`` chip requests, never ``nvidia.com/gpu``;
- scheduling: GKE TPU-slice node selectors + topology labels;
- env: run identity for ``tracking.init()`` plus the ``PTPU_*`` process
  topology block that drives ``jax.distributed.initialize()`` — replacing
  ``TF_CONFIG``/NCCL/MPI bootstrap;
- distributed kinds (tpujob + tfjob/pytorchjob/mpijob compatibility) are
  normalized to one replica topology (``compiler.topology``) instead of
  being delegated to Kubeflow CRs.

Tests assert emitted manifests against golden fixtures — the reference's
"distributed testing without a cluster" trick (SURVEY.md section 4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..compiler.topology import normalize
from ..flow import V1CompiledOperation
from ..flow.run import (
    RunKind,
    V1Service,
    V1SliceSpec,
)
from . import tpu
from .auxiliaries import (
    ARTIFACTS_MOUNT,
    ARTIFACTS_VOLUME,
    CONTEXT_MOUNT,
    CONTEXT_VOLUME,
    DEFAULT_AUX_IMAGE,
    RUN_HOME_MOUNT,
    RUN_HOME_VOLUME,
    SHM_VOLUME,
    get_init_containers,
    get_sidecar_container,
    get_volumes,
)
from .env_vars import identity_env, topology_env

API_VERSION = "core.polyaxon-tpu.io/v1"
OPERATION_KIND = "Operation"
MAIN_CONTAINER = "ptpu-main"
COORDINATOR_PORT = 8476


class ConverterError(ValueError):
    pass


@dataclass
class ConverterConfig:
    """Deployment-level knobs the agent passes to every conversion."""

    namespace: str = "polyaxon-tpu"
    host: Optional[str] = None
    auth_secret: Optional[str] = None
    aux_image: str = DEFAULT_AUX_IMAGE
    default_image: str = "python:3.11-slim"
    artifacts_claim: Optional[str] = None
    artifacts_host_path: Optional[str] = None
    artifacts_root: str = ARTIFACTS_MOUNT
    labels: Dict[str, str] = field(default_factory=dict)
    catalog: Optional[Any] = None  # connections.ConnectionCatalog

    def get_catalog(self):
        if self.catalog is None:
            from ..connections import ConnectionCatalog

            self.catalog = ConnectionCatalog.load()
        return self.catalog


def _labels(config: ConverterConfig, run_uuid: str,
            project: Optional[str]) -> Dict[str, str]:
    labels = {
        "app.kubernetes.io/managed-by": "polyaxon-tpu",
        "polyaxon-tpu/run-uuid": run_uuid,
    }
    if project:
        labels["polyaxon-tpu/project"] = project
    labels.update(config.labels)
    return labels


def _main_container(
    section: Any,
    config: ConverterConfig,
    env: List[Dict[str, Any]],
    *,
    tpu_slice: Optional[V1SliceSpec] = None,
    extra_mounts: Optional[List[Dict[str, Any]]] = None,
    shm: bool = False,
) -> Dict[str, Any]:
    container = getattr(section, "container", None)
    c: Dict[str, Any] = container.to_dict() if container is not None else {}
    c["name"] = MAIN_CONTAINER
    c.setdefault("image", config.default_image)

    c_env = list(c.get("env") or [])
    seen = {e.get("name") for e in c_env}
    c_env.extend(e for e in env if e.get("name") not in seen)
    if "POLYAXON_TPU_HOME" not in seen:
        # Local store on the shared run-home volume — what tracking
        # writes and the sidecar tails.
        c_env.append({"name": "POLYAXON_TPU_HOME",
                      "value": RUN_HOME_MOUNT})
    c["env"] = c_env

    resources = c.get("resources") or {}
    if tpu_slice is not None:
        chips = tpu.tpu_resources(tpu_slice)
        limits = dict(resources.get("limits") or {})
        requests = dict(resources.get("requests") or {})
        limits.update(chips)
        requests.update(chips)
        resources = {**resources, "limits": limits, "requests": requests}
    if resources:
        c["resources"] = resources

    mounts = list(c.get("volumeMounts") or [])
    mounts.append({"name": CONTEXT_VOLUME, "mountPath": CONTEXT_MOUNT})
    mounts.append({"name": RUN_HOME_VOLUME, "mountPath": RUN_HOME_MOUNT})
    mounts.append({"name": ARTIFACTS_VOLUME, "mountPath": ARTIFACTS_MOUNT})
    if shm:
        mounts.append({"name": SHM_VOLUME, "mountPath": "/dev/shm"})
    mounts.extend(extra_mounts or [])
    c["volumeMounts"] = mounts
    return c


def _pod_spec(
    section: Any,
    compiled: V1CompiledOperation,
    config: ConverterConfig,
    env: List[Dict[str, Any]],
    run_uuid: str,
    *,
    tpu_slice: Optional[V1SliceSpec] = None,
) -> Dict[str, Any]:
    """Assemble one pod template spec for a job/service/replica section."""
    environment = getattr(section, "environment", None)
    plugins = compiled.plugins
    shm = bool(plugins and plugins.shm)
    collect_logs = not (plugins and plugins.collect_logs is False)
    collect_artifacts = not (plugins and plugins.collect_artifacts is False)

    # Requested connections: volumes + mounts + root-advertising env
    # (the initializer and user code resolve roots from these).
    conn_volumes: List[Dict[str, Any]] = []
    conn_mounts: List[Dict[str, Any]] = []
    conn_env: List[Dict[str, Any]] = []
    requested = getattr(section, "connections", None) or []
    if requested:
        catalog = config.get_catalog()
        for conn_name in requested:
            volume = catalog.volume_for(conn_name)
            if volume:
                conn_volumes.append(volume)
            mount = catalog.mount_for(conn_name)
            if mount:
                conn_mounts.append(mount)
            conn_env.extend(catalog.env_for(conn_name))
            res_volumes, res_mounts = catalog.resource_volumes_for(conn_name)
            conn_volumes.extend(res_volumes)
            conn_mounts.extend(res_mounts)
        # Volumes dedupe by name inside get_volumes (the merge point);
        # mounts dedupe here since duplicate (volume, path) pairs within
        # one container are redundant (e.g. two connections sharing a
        # secret at the same mount_path).
        seen: set = set()
        conn_mounts = [m for m in conn_mounts
                       if not ((m["name"], m.get("mountPath")) in seen
                               or seen.add((m["name"], m.get("mountPath"))))]

    pod: Dict[str, Any] = {
        "restartPolicy": "Never",
        "containers": [
            _main_container(section, config, env + conn_env,
                            tpu_slice=tpu_slice, shm=shm,
                            extra_mounts=conn_mounts),
        ],
        "volumes": get_volumes(
            shm=shm,
            artifacts_claim=config.artifacts_claim,
            artifacts_host_path=config.artifacts_host_path,
            extra=(getattr(section, "volumes", None) or []) + conn_volumes,
        ),
    }

    inits = get_init_containers(getattr(section, "init", None),
                                aux_image=config.aux_image)
    if inits:
        # Init containers resolve connections too (init.connection):
        # give them the same roots/env and mounts as the main container.
        for ic in inits:
            ic_env = list(ic.get("env") or [])
            present = {e.get("name") for e in ic_env}
            ic_env.extend(e for e in conn_env
                          if e.get("name") not in present)
            ic["env"] = ic_env
            ic.setdefault("volumeMounts", []).extend(conn_mounts)
        pod["initContainers"] = inits

    sidecars = [s.to_dict() for s in (getattr(section, "sidecars", None)
                                      or [])]
    if collect_logs or collect_artifacts:
        sidecars.append(get_sidecar_container(
            run_uuid, aux_image=config.aux_image,
            collect_logs=collect_logs,
            collect_artifacts=collect_artifacts))
    pod["containers"].extend(sidecars)

    node_selector: Dict[str, str] = {}
    tolerations: List[Dict[str, Any]] = []
    if tpu_slice is not None:
        node_selector.update(tpu.slice_node_selector(tpu_slice))
        tolerations.append(tpu.tpu_toleration())

    if environment is not None:
        if environment.node_selector:
            node_selector.update(environment.node_selector)
        if environment.tolerations:
            tolerations.extend(environment.tolerations)
        for src, dst in [
            ("affinity", "affinity"),
            ("node_name", "nodeName"),
            ("service_account_name", "serviceAccountName"),
            ("host_aliases", "hostAliases"),
            ("security_context", "securityContext"),
            ("host_network", "hostNetwork"),
            ("host_pid", "hostPID"),
            ("dns_policy", "dnsPolicy"),
            ("dns_config", "dnsConfig"),
            ("scheduler_name", "schedulerName"),
            ("priority_class_name", "priorityClassName"),
            ("priority", "priority"),
            ("restart_policy", "restartPolicy"),
        ]:
            value = getattr(environment, src, None)
            if value is not None:
                pod[dst] = value
        if environment.image_pull_secrets:
            pod["imagePullSecrets"] = [
                {"name": s} for s in environment.image_pull_secrets]
    if node_selector:
        pod["nodeSelector"] = node_selector
    if tolerations:
        pod["tolerations"] = tolerations
    return pod


def _metadata(compiled: V1CompiledOperation, config: ConverterConfig,
              run_uuid: str, project: Optional[str]) -> Dict[str, Any]:
    environment = getattr(compiled.run, "environment", None)
    annotations = dict(getattr(environment, "annotations", None) or {})
    labels = _labels(config, run_uuid, project)
    if environment is not None and environment.labels:
        labels.update(environment.labels)
    meta = {
        "name": f"ptpu-{run_uuid}",
        "namespace": config.namespace,
        "labels": labels,
    }
    if annotations:
        meta["annotations"] = annotations
    return meta


def _termination(compiled: V1CompiledOperation) -> Dict[str, Any]:
    t = compiled.termination
    if t is None:
        return {}
    out = {}
    if t.max_retries is not None:
        out["backoffLimit"] = t.max_retries
    if t.timeout is not None:
        out["activeDeadlineSeconds"] = t.timeout
    if t.ttl is not None:
        out["ttlSecondsAfterFinished"] = t.ttl
    return out


def convert(
    compiled: V1CompiledOperation,
    run_uuid: str,
    project: Optional[str] = None,
    config: Optional[ConverterConfig] = None,
) -> Dict[str, Any]:
    """Compiled operation -> ``Operation`` custom resource dict."""
    config = config or ConverterConfig()
    run = compiled.run
    kind = compiled.run_kind
    artifacts_path = f"{config.artifacts_root}/{run_uuid}"

    base_env = identity_env(
        run_uuid=run_uuid,
        project=project,
        run_name=compiled.name,
        host=config.host,
        namespace=config.namespace,
        artifacts_path=artifacts_path,
        auth_secret=config.auth_secret,
    )

    spec: Dict[str, Any] = {"runKind": kind}
    spec.update(_termination(compiled))

    if kind == RunKind.JOB or kind in (RunKind.TUNER, RunKind.NOTIFIER,
                                       RunKind.CLEANER):
        spec["template"] = {"spec": _pod_spec(run, compiled, config,
                                              base_env, run_uuid)}
    elif kind == RunKind.SERVICE:
        assert isinstance(run, V1Service)
        spec["template"] = {"spec": _pod_spec(run, compiled, config,
                                              base_env, run_uuid)}
        spec["replicas"] = run.replicas or 1
        if run.ports:
            spec["ports"] = list(run.ports)
    elif kind in RunKind.DISTRIBUTED:
        topology = normalize(run)
        # Pod hostname "{run}-{role}-{i}" + headless-Service subdomain
        # "ptpu-{run}-hs" (set per-pod by the operator) makes the
        # coordinator address resolvable cluster DNS.
        subdomain = f"ptpu-{run_uuid}-hs"
        service_fmt = "{run}-{role}-{index}." + subdomain
        spec["slice"] = {
            "type": topology.slice.type,
            "topology": (topology.slice.topology
                         or tpu.default_topology(
                             topology.slice.type,
                             topology.slice.chips_per_slice)),
            "numSlices": topology.slice.num_slices,
            "chipsPerHost": topology.slice.chips_per_host,
        }
        spec["coordinator"] = {
            "service": topology.coordinator_address(
                service_fmt=service_fmt, run=run_uuid,
                port=COORDINATOR_PORT),
            "port": COORDINATOR_PORT,
        }
        replica_specs: Dict[str, Any] = {}
        for group in topology.groups:
            env = base_env + topology_env(topology, group.role, run_uuid,
                                          port=COORDINATOR_PORT,
                                          service_fmt=service_fmt)
            pod = _pod_spec(group.spec, compiled, config, env, run_uuid,
                            tpu_slice=topology.slice)
            pod["subdomain"] = subdomain
            replica_specs[group.role] = {
                "replicas": group.replicas,
                "template": {"spec": pod},
            }
        spec["replicaSpecs"] = replica_specs
        clean = getattr(run, "clean_pod_policy", None)
        if clean:
            spec["cleanPodPolicy"] = clean
        strategy = getattr(run, "strategy", None)
        if strategy:
            spec["strategy"] = strategy
    else:
        raise ConverterError(
            f"Run kind {kind!r} is not convertible to a k8s resource "
            "(dag/schedule kinds expand in the scheduler)")

    return {
        "apiVersion": API_VERSION,
        "kind": OPERATION_KIND,
        "metadata": _metadata(compiled, config, run_uuid, project),
        "spec": spec,
    }


def cluster_ip_service(cr: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Companion Service for service kinds (notebooks/TensorBoard): the
    operator publishes ``status.endpoints`` as ``<name>.<namespace>``,
    which only resolves if something creates this Service."""
    spec = cr.get("spec", {})
    ports = spec.get("ports")
    if not ports or "replicaSpecs" in spec:
        return None
    meta = cr["metadata"]
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": meta["name"],
            "namespace": meta.get("namespace"),
            "labels": dict(meta.get("labels", {})),
        },
        "spec": {
            "selector": {"polyaxon-tpu/run-uuid":
                         meta["labels"]["polyaxon-tpu/run-uuid"]},
            "ports": [{"port": int(p)} for p in ports],
        },
    }


def headless_service(cr: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Companion headless Service giving replica pods stable DNS —
    the operator applies it alongside distributed Operations."""
    spec = cr.get("spec", {})
    if "replicaSpecs" not in spec:
        return None
    meta = cr["metadata"]
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{meta['name']}-hs",
            "namespace": meta.get("namespace"),
            "labels": dict(meta.get("labels", {})),
        },
        "spec": {
            "clusterIP": "None",
            "selector": {"polyaxon-tpu/run-uuid":
                         meta["labels"]["polyaxon-tpu/run-uuid"]},
            "ports": [{"name": "coordinator",
                       "port": spec["coordinator"]["port"]}],
        },
    }
