"""Minimal Kubernetes REST client (stdlib only).

Parity: the reference agent applies converted resources through the k8s
API server and watches them back (SURVEY.md §2.9, §3.1 step 8-9).  This
client covers exactly the verbs our transport uses — create/get/list/
merge-patch/status-patch/delete plus line-delimited watch — over plain
``http.client``, so the framework adds no kubernetes-package dependency.

Config resolution mirrors kubectl's precedence, trimmed to what a pod or
operator box actually has:

1. explicit ``host``/``token`` arguments,
2. ``PTPU_K8S_HOST`` / ``PTPU_K8S_TOKEN`` / ``PTPU_K8S_NAMESPACE`` env,
3. the in-cluster service account
   (``/var/run/secrets/kubernetes.io/serviceaccount``).

TLS: in-cluster config uses https with the mounted CA.  The stub server
and ``kubectl proxy`` use plain http.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, Optional

OPERATIONS_GROUP = "core.polyaxon-tpu.io"
OPERATIONS_VERSION = "v1"
_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class KubeClient:
    def __init__(self, host: Optional[str] = None,
                 token: Optional[str] = None,
                 namespace: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 timeout: float = 10.0):
        env = os.environ
        self.host = (host or env.get("PTPU_K8S_HOST") or
                     self._in_cluster_host() or "").rstrip("/")
        if not self.host:
            raise KubeApiError(0, "no API server host configured "
                                  "(PTPU_K8S_HOST or in-cluster)")
        self.token = token or env.get("PTPU_K8S_TOKEN") or \
            self._read_sa("token")
        self.namespace = namespace or env.get("PTPU_K8S_NAMESPACE") or \
            self._read_sa("namespace") or "default"
        self.timeout = timeout
        ca = ca_file or (os.path.join(_SA_DIR, "ca.crt")
                         if os.path.exists(os.path.join(_SA_DIR, "ca.crt"))
                         else None)
        self._ctx: Optional[ssl.SSLContext] = None
        if self.host.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca)

    @staticmethod
    def _in_cluster_host() -> Optional[str]:
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return f"https://{host}:{port}" if host else None

    @staticmethod
    def _read_sa(name: str) -> Optional[str]:
        try:
            with open(os.path.join(_SA_DIR, name)) as f:
                return f.read().strip()
        except OSError:
            return None

    # -- plumbing ----------------------------------------------------------

    def _path(self, plural: str, name: Optional[str] = None,
              group: str = "", subresource: Optional[str] = None,
              namespace: Optional[str] = None) -> str:
        ns = namespace or self.namespace
        base = (f"/apis/{group}/{OPERATIONS_VERSION}" if group
                else "/api/v1")
        path = f"{base}/namespaces/{ns}/{plural}"
        if name:
            path += f"/{name}"
        if subresource:
            path += f"/{subresource}"
        return path

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 content_type: str = "application/json",
                 timeout: Optional[float] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.host + path, data=data,
                                     method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ctx)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            try:
                detail = json.loads(detail).get("message", detail)
            except ValueError:
                pass
            raise KubeApiError(e.code, detail) from None
        except urllib.error.URLError as e:
            raise KubeApiError(0, str(e.reason)) from None

    def _json(self, method: str, path: str, body: Optional[dict] = None,
              content_type: str = "application/json") -> Dict[str, Any]:
        with self._request(method, path, body, content_type) as resp:
            return json.loads(resp.read() or b"{}")

    # -- verbs -------------------------------------------------------------

    def create(self, plural: str, obj: dict, group: str = "",
               namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("POST",
                          self._path(plural, group=group,
                                     namespace=namespace), obj)

    def get(self, plural: str, name: str, group: str = "",
            namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("GET", self._path(plural, name, group,
                                            namespace=namespace))

    def list(self, plural: str, group: str = "",
             namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("GET", self._path(plural, group=group,
                                            namespace=namespace))

    def patch(self, plural: str, name: str, patch: dict, group: str = "",
              namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("PATCH", self._path(plural, name, group,
                                              namespace=namespace),
                          patch, "application/merge-patch+json")

    def patch_status(self, plural: str, name: str, status: dict,
                     group: str = "",
                     namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("PATCH",
                          self._path(plural, name, group, "status",
                                     namespace=namespace),
                          {"status": status},
                          "application/merge-patch+json")

    def delete(self, plural: str, name: str, group: str = "",
               namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("DELETE", self._path(plural, name, group,
                                               namespace=namespace))

    def watch(self, plural: str, group: str = "",
              resource_version: Optional[str] = None,
              timeout_seconds: float = 5.0,
              namespace: Optional[str] = None) -> Iterator[Dict[str, Any]]:
        """Yield ``{"type": ..., "object": ...}`` events until the server
        closes the stream (bounded by ``timeout_seconds``)."""
        path = self._path(plural, group=group, namespace=namespace)
        path += f"?watch=true&timeoutSeconds={timeout_seconds:g}"
        if resource_version:
            path += f"&resourceVersion={resource_version}"
        with self._request("GET", path,
                           timeout=timeout_seconds + 5) as resp:
            for raw in resp:
                line = raw.strip()
                if line:
                    yield json.loads(line)
