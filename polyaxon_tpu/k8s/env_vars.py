"""Env-var injection blocks for converted pods.

Two blocks (SURVEY.md 2.9/2.10, call stack 3.2):

- **run identity** — lets in-container ``tracking.init()`` self-identify
  (run UUID, project, API host, auth) without arguments;
- **process topology** — the ``PTPU_*`` block that
  ``parallel.bootstrap.initialize_from_env()`` turns into
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``
  — the north-star replacement for ``TF_CONFIG``/NCCL/MPI bootstrap.

Per-pod fields (``PTPU_PROCESS_ID`` / ``PTPU_REPLICA_INDEX``) are
completed by the operator when it stamps out one pod per replica; the
converter emits everything role-level.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..compiler.topology import ProcessTopology

ENV_RUN_UUID = "POLYAXON_TPU_RUN_UUID"
ENV_RUN_NAME = "POLYAXON_TPU_RUN_NAME"
ENV_PROJECT = "POLYAXON_TPU_PROJECT"
ENV_HOST = "POLYAXON_TPU_HOST"
ENV_AUTH_TOKEN = "POLYAXON_TPU_AUTH_TOKEN"
ENV_NAMESPACE = "POLYAXON_TPU_NAMESPACE"
ENV_ARTIFACTS_PATH = "POLYAXON_TPU_ARTIFACTS_PATH"
ENV_CONTEXT_PATH = "POLYAXON_TPU_CONTEXT_PATH"


def env_list(env: Dict[str, str]) -> List[Dict[str, Any]]:
    return [{"name": k, "value": v} for k, v in env.items()]


def identity_env(
    run_uuid: str,
    project: Optional[str] = None,
    run_name: Optional[str] = None,
    host: Optional[str] = None,
    namespace: Optional[str] = None,
    artifacts_path: Optional[str] = None,
    auth_secret: Optional[str] = None,
) -> List[Dict[str, Any]]:
    env: List[Dict[str, Any]] = [{"name": ENV_RUN_UUID, "value": run_uuid}]
    if run_name:
        env.append({"name": ENV_RUN_NAME, "value": run_name})
    if project:
        env.append({"name": ENV_PROJECT, "value": project})
    if host:
        env.append({"name": ENV_HOST, "value": host})
    if namespace:
        env.append({"name": ENV_NAMESPACE, "value": namespace})
    if artifacts_path:
        env.append({"name": ENV_ARTIFACTS_PATH, "value": artifacts_path})
    if auth_secret:
        env.append({
            "name": ENV_AUTH_TOKEN,
            "valueFrom": {"secretKeyRef": {"name": auth_secret,
                                           "key": "token"}},
        })
    env.append({
        "name": "POLYAXON_TPU_POD_ID",
        "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
    })
    return env


def topology_env(topology: ProcessTopology, role: str,
                 run_uuid: str, port: int = 8476,
                 service_fmt: str = "{run}-{role}-{index}",
                 ) -> List[Dict[str, Any]]:
    """Role-level PTPU_* block (index-free; operator adds per-pod ids)."""
    env = topology.process_env(role, 0, run=run_uuid, port=port,
                               service_fmt=service_fmt)
    env.pop("PTPU_PROCESS_ID")
    env.pop("PTPU_REPLICA_INDEX")
    return env_list(env)
