"""TPU slice scheduling vocabulary: resources, selectors, topologies.

The reference's converter emits ``nvidia.com/gpu`` resource requests
(SURVEY.md 2.10 / north-star); the TPU-native converter instead emits
``google.com/tpu`` chip requests plus the GKE TPU-slice node selectors
(``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology``) that
the GKE scheduler uses to place pods onto slice hosts.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..flow.run import V1SliceSpec

TPU_RESOURCE = "google.com/tpu"
ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

# Public GKE accelerator values per TPU generation (family prefix of the
# slice ``type``).  3D-torus generations take XxYxZ topologies; the lite
# (cost-optimized) generations are 2D.
_ACCELERATORS = {
    "v6e": ("tpu-v6e-slice", 2),
    "v5litepod": ("tpu-v5-lite-podslice", 2),
    "v5e": ("tpu-v5-lite-podslice", 2),
    "v5p": ("tpu-v5p-slice", 3),
    "v4": ("tpu-v4-podslice", 3),
    "v3": ("tpu-v3-slice", 2),
}


class SliceError(ValueError):
    pass


def _family(slice_type: str) -> str:
    return slice_type.split("-", 1)[0].lower()


def accelerator_for(slice_type: str) -> str:
    fam = _family(slice_type)
    if fam not in _ACCELERATORS:
        raise SliceError(
            f"Unknown TPU slice family {fam!r} (from {slice_type!r}); "
            f"known: {sorted(_ACCELERATORS)}")
    return _ACCELERATORS[fam][0]


def default_topology(slice_type: str, chips: int) -> str:
    """Near-square power-of-two factorization of the chip count onto the
    generation's torus rank (2D for lite parts, 3D for v4/v5p)."""
    fam = _family(slice_type)
    rank = _ACCELERATORS.get(fam, ("", 2))[1]
    if chips <= 0 or chips & (chips - 1):
        raise SliceError(
            f"Cannot derive a torus topology for {chips} chips; give "
            "slice.topology explicitly")
    dims = [1] * rank
    remaining = chips
    i = 0
    while remaining > 1:
        dims[i % rank] *= 2
        remaining //= 2
        i += 1
    dims.sort()
    return "x".join(str(d) for d in dims)


def slice_node_selector(spec: V1SliceSpec) -> Dict[str, str]:
    topology = spec.topology or default_topology(spec.type,
                                                 spec.chips_per_slice)
    return {
        ACCELERATOR_LABEL: accelerator_for(spec.type),
        TOPOLOGY_LABEL: topology,
    }


def tpu_resources(spec: V1SliceSpec) -> Dict[str, int]:
    """Per-pod chip request: each pod is one slice host."""
    return {TPU_RESOURCE: spec.chips_per_host}


def tpu_toleration() -> Dict[str, str]:
    return {"key": TPU_RESOURCE, "operator": "Exists",
            "effect": "NoSchedule"}
