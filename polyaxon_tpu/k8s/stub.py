"""In-memory kube-apiserver stub (envtest equivalent).

The reference operator is tested against controller-runtime's ``envtest``
— a real API server with no kubelet (SURVEY.md §4 "Operator (Go)").
This module is our equivalent: a stdlib HTTP server speaking enough of
the Kubernetes REST API for the agent's ``KubeBackend`` and the C++
operator's ``--kube-api`` mode to run golden interactions without a
cluster:

- typed REST paths — core ``/api/v1/namespaces/{ns}/{plural}`` and
  group ``/apis/{group}/{version}/namespaces/{ns}/{plural}``;
- verbs: POST (create, 409 on conflict), GET (read/list), PUT (replace),
  PATCH (``application/merge-patch+json``), DELETE, and the ``/status``
  subresource (spec writes bump ``metadata.generation``, status writes
  do not — the operator's change detection relies on this, matching k8s
  semantics);
- ``?watch=true`` list streams ``{"type": ..., "object": ...}`` JSON
  lines (chunked), replaying history after ``resourceVersion``;
- optional bearer-token auth (401 without it) so RBAC wiring is
  testable;
- a **fake kubelet**: created pods go Running immediately and Succeeded
  after ``pod_run_seconds`` — unless annotated:
    ``stub.polyaxon-tpu/fail``: "true"      -> Failed (exit 1)
    ``stub.polyaxon-tpu/run-seconds``: "S"  -> per-pod run time
    ``stub.polyaxon-tpu/hold``: "true"      -> stays Running until
                                               released or deleted
  which is exactly the knob chaos tests need to kill pods mid-gang.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

ANN_FAIL = "stub.polyaxon-tpu/fail"
ANN_RUN_SECONDS = "stub.polyaxon-tpu/run-seconds"
ANN_HOLD = "stub.polyaxon-tpu/hold"

# /api/v1/... (core) or /apis/{group}/{version}/...
_PATH = re.compile(
    r"^/(?:api/v1|apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"/namespaces/(?P<ns>[^/]+)/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?(?:/(?P<sub>status))?$")


def _deep_merge(dst: Any, patch: Any) -> Any:
    """RFC 7386 merge patch: null deletes, dicts recurse, else replace."""
    if not isinstance(patch, dict) or not isinstance(dst, dict):
        return patch
    out = dict(dst)
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        else:
            out[key] = _deep_merge(out.get(key), value)
    return out


class _State:
    """Resource store + watch event log, guarded by one lock."""

    def __init__(self):
        self.lock = threading.RLock()
        # (group, ns, plural) -> {name: object}
        self.resources: Dict[Tuple[str, str, str], Dict[str, dict]] = {}
        self.events: List[dict] = []  # {"type", "object", "rv"}
        self.rv = 0
        self.requests: List[Tuple[str, str]] = []  # (method, path) golden log

    def next_rv(self) -> int:
        self.rv += 1
        return self.rv

    def record(self, event_type: str, obj: dict) -> None:
        self.events.append({"type": event_type, "object": obj,
                            "rv": int(obj["metadata"]["resourceVersion"])})


class StubApiServer:
    """Threaded stub apiserver; use as a context manager in tests."""

    def __init__(self, token: Optional[str] = None,
                 pod_run_seconds: float = 0.15,
                 kubelet: bool = True):
        self.state = _State()
        self.token = token
        self.pod_run_seconds = pod_run_seconds
        self._kubelet_on = kubelet
        state, stub = self.state, self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # silence
                pass

            def _deny(self, code: int, reason: str):
                body = json.dumps({"kind": "Status", "code": code,
                                   "message": reason}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send(self, code: int, obj: dict):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                if stub.token is None:
                    return True
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {stub.token}"

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b"{}"
                return json.loads(raw or b"{}")

            def _route(self):
                path, _, query = self.path.partition("?")
                match = _PATH.match(path)
                params = dict(p.split("=", 1) for p in query.split("&")
                              if "=" in p)
                return match, params

            def _handle(self, method: str):
                with stub.state.lock:
                    stub.state.requests.append((method, self.path))
                if not self._authed():
                    return self._deny(401, "Unauthorized")
                match, params = self._route()
                if not match:
                    return self._deny(404, f"no route: {self.path}")
                group = match.group("group") or ""
                key = (group, match.group("ns"), match.group("plural"))
                name, sub = match.group("name"), match.group("sub")
                try:
                    getattr(self, f"_do_{method.lower()}")(
                        key, name, sub, params)
                except BrokenPipeError:  # watcher went away
                    pass

            def do_GET(self):  # noqa: N802
                self._handle("GET")

            def do_POST(self):  # noqa: N802
                self._handle("POST")

            def do_PUT(self):  # noqa: N802
                self._handle("PUT")

            def do_PATCH(self):  # noqa: N802
                self._handle("PATCH")

            def do_DELETE(self):  # noqa: N802
                self._handle("DELETE")

            # -- verbs ----------------------------------------------------

            def _do_get(self, key, name, sub, params):
                stub._kubelet_tick()
                with state.lock:
                    items = state.resources.get(key, {})
                    if name:
                        obj = items.get(name)
                        if obj is None:
                            return self._deny(404, f"{name} not found")
                        return self._send(200, obj)
                    if params.get("watch") == "true":
                        since = int(params.get("resourceVersion") or 0)
                        snapshot = [e for e in state.events
                                    if e["rv"] > since
                                    and stub._event_key(e) == key]
                    else:
                        kind = key[2].rstrip("s").capitalize() + "List"
                        return self._send(200, {
                            "kind": kind,
                            "metadata": {"resourceVersion": str(state.rv)},
                            "items": list(items.values())})
                # watch: replay history, then poll for new events until
                # the client hangs up (timeoutSeconds caps it).
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                deadline = time.time() + float(
                    params.get("timeoutSeconds") or 5)
                sent = 0
                while time.time() < deadline:
                    for event in snapshot[sent:]:
                        line = json.dumps(
                            {"type": event["type"],
                             "object": event["object"]}).encode() + b"\n"
                        self.wfile.write(
                            hex(len(line))[2:].encode() + b"\r\n" + line
                            + b"\r\n")
                        self.wfile.flush()
                    sent = len(snapshot)
                    time.sleep(0.05)
                    stub._kubelet_tick()
                    with state.lock:
                        since = snapshot[-1]["rv"] if snapshot else since
                        snapshot += [e for e in state.events
                                     if e["rv"] > since
                                     and stub._event_key(e) == key]
                self.wfile.write(b"0\r\n\r\n")

            def _do_post(self, key, name, sub, params):
                obj = self._body()
                with state.lock:
                    items = state.resources.setdefault(key, {})
                    obj_name = obj.get("metadata", {}).get("name")
                    if not obj_name:
                        return self._deny(422, "metadata.name required")
                    if obj_name in items:
                        return self._deny(409, f"{obj_name} exists")
                    meta = obj.setdefault("metadata", {})
                    if meta.get("namespace") and \
                            meta["namespace"] != key[1]:
                        # real apiserver semantics: body namespace must
                        # match the request path
                        return self._deny(
                            400, f"namespace {meta['namespace']!r} does "
                                 f"not match request {key[1]!r}")
                    meta["resourceVersion"] = str(state.next_rv())
                    meta["generation"] = 1
                    meta["namespace"] = key[1]
                    meta["creationTimestamp"] = time.time()
                    if key[2] == "pods":
                        obj.setdefault("status", {})["phase"] = "Pending"
                        meta["_stub_created"] = time.time()
                    items[obj_name] = obj
                    state.record("ADDED", obj)
                self._send(201, obj)

            def _do_put(self, key, name, sub, params):
                if not name:
                    return self._deny(405, "PUT needs a name")
                body = self._body()
                with state.lock:
                    items = state.resources.get(key, {})
                    obj = items.get(name)
                    if obj is None:
                        return self._deny(404, f"{name} not found")
                    self._apply_update(key, obj, body, sub)
                    self._send(200, obj)

            def _do_patch(self, key, name, sub, params):
                if not name:
                    return self._deny(405, "PATCH needs a name")
                patch = self._body()
                with state.lock:
                    items = state.resources.get(key, {})
                    obj = items.get(name)
                    if obj is None:
                        return self._deny(404, f"{name} not found")
                    if sub == "status":
                        merged = dict(obj)
                        merged["status"] = _deep_merge(
                            obj.get("status") or {},
                            patch.get("status") or {})
                    else:
                        merged = _deep_merge(obj, patch)
                        merged["metadata"] = obj["metadata"]  # immutable-ish
                    self._apply_update(key, obj, merged, sub)
                    self._send(200, obj)

            def _apply_update(self, key, obj, new, sub):
                """In-place update honoring generation semantics."""
                meta = obj["metadata"]
                old_spec = json.dumps(obj.get("spec"), sort_keys=True)
                if sub == "status":
                    obj["status"] = new.get("status") or {}
                else:
                    obj["spec"] = new.get("spec", obj.get("spec"))
                    if "status" in new and new is not obj:
                        pass  # spec endpoint never writes status
                meta["resourceVersion"] = str(state.next_rv())
                if json.dumps(obj.get("spec"), sort_keys=True) != old_spec:
                    meta["generation"] = int(meta.get("generation", 1)) + 1
                state.record("MODIFIED", obj)

            def _do_delete(self, key, name, sub, params):
                with state.lock:
                    items = state.resources.get(key, {})
                    obj = items.pop(name, None) if name else None
                    if obj is None:
                        return self._deny(404, f"{name} not found")
                    obj["metadata"]["resourceVersion"] = str(state.next_rv())
                    state.record("DELETED", obj)
                self._send(200, {"kind": "Status", "status": "Success"})

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    # -- fake kubelet ------------------------------------------------------

    def _event_key(self, event) -> Tuple[str, str, str]:
        obj = event["object"]
        kind = obj.get("kind", "")
        plural = {"Pod": "pods", "Service": "services"}.get(
            kind, kind.lower() + "s")
        group = ""
        api_version = obj.get("apiVersion", "v1")
        if "/" in api_version:
            group = api_version.split("/", 1)[0]
        return (group, obj["metadata"].get("namespace", "default"), plural)

    def _kubelet_tick(self) -> None:
        """Advance pod phases (Pending -> Running -> Succeeded/Failed)."""
        if not self._kubelet_on:
            return
        now = time.time()
        with self.state.lock:
            for key, items in self.state.resources.items():
                if key[2] != "pods":
                    continue
                for pod in items.values():
                    phase = pod.get("status", {}).get("phase")
                    meta = pod["metadata"]
                    ann = meta.get("annotations") or {}
                    age = now - meta.get("_stub_created", now)
                    run_for = float(ann.get(ANN_RUN_SECONDS,
                                            self.pod_run_seconds))
                    new = None
                    if phase == "Pending":
                        new = "Running"
                    elif phase == "Running" and age >= run_for and \
                            ann.get(ANN_HOLD) != "true":
                        new = ("Failed" if ann.get(ANN_FAIL) == "true"
                               else "Succeeded")
                    if new:
                        pod.setdefault("status", {})["phase"] = new
                        if new == "Failed":
                            pod["status"]["containerStatuses"] = [
                                {"name": "ptpu-main", "state": {
                                    "terminated": {"exitCode": 1}}}]
                        elif new == "Succeeded":
                            pod["status"]["containerStatuses"] = [
                                {"name": "ptpu-main", "state": {
                                    "terminated": {"exitCode": 0}}}]
                        meta["resourceVersion"] = str(self.state.next_rv())
                        self.state.record("MODIFIED", pod)

    # -- test helpers ------------------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def objects(self, plural: str, namespace: str = "default",
                group: str = "") -> Dict[str, dict]:
        with self.state.lock:
            return dict(self.state.resources.get(
                (group, namespace, plural), {}))

    def set_pod_phase(self, name: str, phase: str,
                      namespace: str = "default",
                      exit_code: Optional[int] = None) -> None:
        """Chaos knob: force a pod phase (e.g. kill mid-gang)."""
        with self.state.lock:
            pod = self.state.resources.get(
                ("", namespace, "pods"), {}).get(name)
            if pod is None:
                raise KeyError(name)
            pod.setdefault("status", {})["phase"] = phase
            if exit_code is not None:
                pod["status"]["containerStatuses"] = [
                    {"name": "ptpu-main",
                     "state": {"terminated": {"exitCode": exit_code}}}]
            pod["metadata"]["resourceVersion"] = str(self.state.next_rv())
            self.state.record("MODIFIED", pod)

    def requests_log(self) -> List[Tuple[str, str]]:
        with self.state.lock:
            return list(self.state.requests)

    def start(self) -> "StubApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "StubApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
