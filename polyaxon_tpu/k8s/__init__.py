"""Kubernetes layer: converter + custom resources (SURVEY.md L2).

Turns compiled operations into ``Operation`` CRs with TPU-slice
scheduling (``google.com/tpu`` resources, GKE topology selectors) and
env injection for tracking + ``jax.distributed`` bootstrap.  The C++
operator (``operator/``) reconciles these CRs into pods.
"""

from .converter import (
    API_VERSION,
    COORDINATOR_PORT,
    MAIN_CONTAINER,
    OPERATION_KIND,
    ConverterConfig,
    ConverterError,
    convert,
    cluster_ip_service,
    headless_service,
)
from .tpu import (
    ACCELERATOR_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    SliceError,
    accelerator_for,
    default_topology,
    slice_node_selector,
    tpu_resources,
)

__all__ = [
    "API_VERSION",
    "ACCELERATOR_LABEL",
    "COORDINATOR_PORT",
    "ConverterConfig",
    "ConverterError",
    "MAIN_CONTAINER",
    "OPERATION_KIND",
    "SliceError",
    "TOPOLOGY_LABEL",
    "TPU_RESOURCE",
    "accelerator_for",
    "convert",
    "default_topology",
    "cluster_ip_service",
    "headless_service",
    "slice_node_selector",
    "tpu_resources",
]
