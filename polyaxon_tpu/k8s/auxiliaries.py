"""Auxiliary containers: init containers and the artifacts/logs sidecar.

Parity: reference ``get_init_container()`` / ``get_sidecar_container()``
(SURVEY.md 2.10 — expected at ``polyaxon/_k8s/converter/`` auxiliaries,
unverified).  Init actions are executed by ``polyaxon_tpu.initializer``
(in-repo, so the same image as the main container works as the aux
image); the sidecar is ``polyaxon_tpu.sidecar``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..flow.environment import V1Init
from ..flow.k8s_refs import V1Container

CONTEXT_VOLUME = "ptpu-context"
CONTEXT_MOUNT = "/ptpu-context"
ARTIFACTS_VOLUME = "ptpu-artifacts"
ARTIFACTS_MOUNT = "/ptpu-artifacts"
# Shared emptyDir holding the run's LOCAL store (tracking events, logs,
# outputs): the main container writes here (POLYAXON_TPU_HOME) and the
# sidecar tails it — without a shared volume the sidecar would see
# nothing to upload.
RUN_HOME_VOLUME = "ptpu-home"
RUN_HOME_MOUNT = "/ptpu-home"
SHM_VOLUME = "ptpu-shm"

DEFAULT_AUX_IMAGE = "polyaxon-tpu/aux:latest"


def _aux_container(name: str, image: str, argv: List[str],
                   env: Optional[List[Dict[str, Any]]] = None
                   ) -> Dict[str, Any]:
    return {
        "name": name,
        "image": image,
        "command": ["python", "-m", "polyaxon_tpu.initializer"],
        "args": argv,
        "env": env or [],
        "volumeMounts": [
            {"name": CONTEXT_VOLUME, "mountPath": CONTEXT_MOUNT},
            {"name": ARTIFACTS_VOLUME, "mountPath": ARTIFACTS_MOUNT},
        ],
    }


def get_init_containers(
    inits: Optional[List[V1Init]],
    aux_image: str = DEFAULT_AUX_IMAGE,
) -> List[Dict[str, Any]]:
    containers: List[Dict[str, Any]] = []
    for idx, init in enumerate(inits or []):
        name = f"ptpu-init-{idx}"
        if init.container is not None:
            # Custom init container passes through, with the shared
            # context/artifacts mounts appended.
            c = init.container.to_dict()  # camelCase aliases built in
            c.setdefault("name", name)
            mounts = c.setdefault("volumeMounts", [])
            mounts.extend([
                {"name": CONTEXT_VOLUME, "mountPath": CONTEXT_MOUNT},
                {"name": ARTIFACTS_VOLUME, "mountPath": ARTIFACTS_MOUNT},
            ])
            containers.append(c)
            continue
        dest = init.path or CONTEXT_MOUNT
        if init.git is not None:
            argv = ["git", "--url", init.git.url or "", "--dest", dest]
            if init.git.revision:
                argv += ["--revision", init.git.revision]
            for flag in init.git.flags or []:
                argv += ["--flag", flag]
        elif init.artifacts is not None:
            argv = ["artifacts", "--dest", dest]
            for f in init.artifacts.files or []:
                argv += ["--file", str(f)]
            for d in init.artifacts.dirs or []:
                argv += ["--dir", str(d)]
            if init.connection:
                argv += ["--connection", init.connection]
        elif init.file is not None:
            argv = ["file", "--dest", dest,
                    "--filename", init.file.filename or "file",
                    "--content", init.file.content or ""]
            if init.file.chmod:
                argv += ["--chmod", init.file.chmod]
        elif init.dockerfile is not None:
            argv = ["dockerfile", "--dest", dest,
                    "--spec", json.dumps(init.dockerfile.to_dict())]
        elif init.tensorboard is not None:
            argv = ["tensorboard", "--dest", dest,
                    "--spec", json.dumps(init.tensorboard.to_dict())]
        elif init.connection:
            argv = ["artifacts", "--dest", dest,
                    "--connection", init.connection]
        else:
            raise ValueError(f"Init entry {idx} declares no action")
        containers.append(_aux_container(name, aux_image, argv))
    return containers


def get_sidecar_container(
    run_uuid: str,
    aux_image: str = DEFAULT_AUX_IMAGE,
    sync_interval: int = 10,
    collect_logs: bool = True,
    collect_artifacts: bool = True,
) -> Dict[str, Any]:
    """Watcher-uploader streaming run events/logs to the artifacts store."""
    return {
        "name": "ptpu-sidecar",
        "image": aux_image,
        "command": ["python", "-m", "polyaxon_tpu.sidecar"],
        "args": [
            "--run-uuid", run_uuid,
            "--local-root", f"{RUN_HOME_MOUNT}/runs/{run_uuid}",
            "--store-root", ARTIFACTS_MOUNT,
            "--sync-interval", str(sync_interval),
            "--collect-logs", "true" if collect_logs else "false",
            "--collect-artifacts", "true" if collect_artifacts else "false",
        ],
        "env": [],
        "volumeMounts": [
            {"name": RUN_HOME_VOLUME, "mountPath": RUN_HOME_MOUNT},
            {"name": ARTIFACTS_VOLUME, "mountPath": ARTIFACTS_MOUNT},
        ],
    }


def get_volumes(
    *,
    shm: bool = False,
    artifacts_claim: Optional[str] = None,
    artifacts_host_path: Optional[str] = None,
    extra: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    volumes: List[Dict[str, Any]] = [
        {"name": CONTEXT_VOLUME, "emptyDir": {}},
        {"name": RUN_HOME_VOLUME, "emptyDir": {}},
    ]
    if artifacts_claim:
        volumes.append({
            "name": ARTIFACTS_VOLUME,
            "persistentVolumeClaim": {"claimName": artifacts_claim},
        })
    elif artifacts_host_path:
        volumes.append({
            "name": ARTIFACTS_VOLUME,
            "hostPath": {"path": artifacts_host_path},
        })
    else:
        volumes.append({"name": ARTIFACTS_VOLUME, "emptyDir": {}})
    if shm:
        volumes.append({
            "name": SHM_VOLUME,
            "emptyDir": {"medium": "Memory"},
        })
    volumes.extend(extra or [])
    # Dedupe at the merge point: builtin + user section + connection
    # volumes can collide on name (e.g. two connections sharing one
    # secret), and the k8s API rejects duplicate volumes[].name.
    seen: set = set()
    return [v for v in volumes
            if not (v["name"] in seen or seen.add(v["name"]))]
