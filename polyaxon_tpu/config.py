"""Client/deployment configuration: env layering + home config.

Parity: reference ``ClientConfig`` / env vars / home managers
(SURVEY.md 2.15/5.6; expected at ``polyaxon/_env_vars``, ``_managers/``
— unverified).  Layering, lowest to highest precedence:

    1. defaults
    2. home config file (``$POLYAXON_TPU_HOME/config.json``)
    3. ``POLYAXON_TPU_*`` environment variables
    4. explicit constructor kwargs

TPU additions: default mesh/topology settings (slice type, strategy
axes) ride the same config so a deployment can pin them fleet-wide.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

ENV_PREFIX = "POLYAXON_TPU_"

_ENV_KEYS = {
    "host": "HOST",
    "token": "AUTH_TOKEN",
    "project": "PROJECT",
    "namespace": "NAMESPACE",
    "timeout": "TIMEOUT",
    "verify_ssl": "VERIFY_SSL",
    "debug": "DEBUG",
    "default_slice_type": "DEFAULT_SLICE_TYPE",
    "default_strategy": "DEFAULT_STRATEGY",
    "connections_file": "CONNECTIONS_FILE",
}

_BOOLS = {"verify_ssl", "debug"}
_FLOATS = {"timeout"}
_JSON = {"default_strategy"}


def home_dir() -> str:
    from .client.store import default_home

    return default_home()


def _config_path() -> str:
    return os.path.join(home_dir(), "config.json")


def _write_config(path: str, payload: Dict[str, Any]) -> None:
    """Atomic write, 0600: config.json may hold the API bearer token, so
    it must not be readable by other local users (ADVICE r1)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    os.chmod(path, 0o600)


def _coerce(key: str, value: Any) -> Any:
    if value is None or not isinstance(value, str):
        return value
    if key in _BOOLS:
        return value.lower() in ("1", "true", "yes", "on")
    if key in _FLOATS:
        return float(value)
    if key in _JSON:
        try:
            return json.loads(value)
        except ValueError:
            return value
    return value


@dataclass
class ClientConfig:
    host: Optional[str] = None
    token: Optional[str] = None
    project: str = "default"
    namespace: str = "polyaxon-tpu"
    timeout: float = 30.0
    verify_ssl: bool = True
    debug: bool = False
    # TPU-wide defaults
    default_slice_type: str = "v5litepod-8"
    default_strategy: Dict[str, int] = field(default_factory=dict)
    connections_file: Optional[str] = None

    @staticmethod
    def read_file_layer() -> Dict[str, Any]:
        """Raw key -> value pairs persisted in the home config file."""
        path = _config_path()
        if not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                stored = json.load(f)
            return {k: v for k, v in stored.items() if k in _ENV_KEYS}
        except (OSError, ValueError):
            return {}

    @classmethod
    def load(cls, **overrides: Any) -> "ClientConfig":
        """Apply the full layering."""
        values: Dict[str, Any] = dict(cls.read_file_layer())
        for key, suffix in _ENV_KEYS.items():
            env_val = os.environ.get(ENV_PREFIX + suffix)
            if env_val is not None:
                values[key] = _coerce(key, env_val)
        values.update({k: v for k, v in overrides.items()
                       if v is not None})
        return cls(**values)

    def save(self) -> str:
        """Persist to the home config file (the `config set` surface)."""
        path = _config_path()
        payload = {k: v for k, v in dataclasses.asdict(self).items()
                   if v not in (None, {}, [])}
        _write_config(path, payload)
        return path

    @classmethod
    def set_file_values(cls, pairs: Dict[str, str]) -> str:
        """Mutate ONLY the file layer: never freeze env values or
        package defaults into config.json (a stale exported token/host
        must not outlive its shell)."""
        stored = cls.read_file_layer()
        for key, raw in pairs.items():
            if key not in _ENV_KEYS:
                raise KeyError(
                    f"Unknown config key {key!r}; known: "
                    f"{sorted(_ENV_KEYS)}")
            stored[key] = _coerce(key, raw)
        path = _config_path()
        _write_config(path, stored)
        return path

    @classmethod
    def unset_file_values(cls, keys) -> str:
        """Remove keys from the file layer (atomic write)."""
        stored = cls.read_file_layer()
        for key in keys:
            stored.pop(key, None)
        path = _config_path()
        _write_config(path, stored)
        return path

    def set_value(self, key: str, raw: str) -> None:
        if key not in _ENV_KEYS:
            raise KeyError(
                f"Unknown config key {key!r}; known: {sorted(_ENV_KEYS)}")
        setattr(self, key, _coerce(key, raw))

    @property
    def in_cluster(self) -> bool:
        return bool(os.environ.get(ENV_PREFIX + "RUN_UUID"))
