"""Deployment manifest generation: ``ptpu admin deploy``.

Parity: reference deploy/config subsystem (SURVEY.md 2.15 — helm charts
+ ``polyaxon deploy``; expected at ``polyaxon/_deploy/``, unverified).
No helm here: a typed ``DeploymentConfig`` renders the exact k8s
manifests for the three services this framework runs in-cluster —
control plane (API+scheduler), agent, and the native operator — plus
the Operation CRD, RBAC, and the auth secret skeleton.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class DeploymentConfig:
    namespace: str = "polyaxon-tpu"
    image: str = "polyaxon-tpu/core:latest"
    operator_image: str = "polyaxon-tpu/operator:latest"
    api_port: int = 8000
    replicas_api: int = 1
    agent_name: str = "agent-0"
    artifacts_claim: Optional[str] = None
    service_account: str = "polyaxon-tpu"
    env: Dict[str, str] = field(default_factory=dict)
    # The API listens on 0.0.0.0 behind a Service, so a bearer token is
    # mandatory in-cluster (ADVICE r1: unauthenticated remote store access).
    # None -> a random token is generated at render time.
    auth_secret_name: str = "polyaxon-tpu-auth"
    auth_token: Optional[str] = None
    # Cluster transport: "kube" — agent applies Operation CRs through the
    # kube-apiserver and the operator reconciles them into real pods
    # (``--kube-api``); "manifest" — single-box file protocol over a
    # shared emptyDir (no pods are created; everything runs inside the
    # agent pod).
    transport: str = "kube"
    # The operator's HTTP client is plaintext; in-cluster it reaches the
    # apiserver through a kubectl-proxy sidecar on localhost.
    kube_proxy_port: int = 8001


def _meta(name: str, config: DeploymentConfig) -> Dict[str, Any]:
    return {
        "name": name,
        "namespace": config.namespace,
        "labels": {"app.kubernetes.io/name": name,
                   "app.kubernetes.io/part-of": "polyaxon-tpu"},
    }


def _env_list(config: DeploymentConfig,
              extra: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
    env = {**config.env, **(extra or {})}
    out: List[Dict[str, Any]] = [{"name": k, "value": v}
                                 for k, v in sorted(env.items())]
    out.append({"name": "POLYAXON_TPU_AUTH_TOKEN",
                "valueFrom": {"secretKeyRef":
                              {"name": config.auth_secret_name,
                               "key": "token"}}})
    return out


def auth_secret(config: DeploymentConfig) -> Dict[str, Any]:
    """Pass ``auth_token`` (or export POLYAXON_TPU_AUTH_TOKEN) to keep the
    credential stable across re-renders; otherwise each render generates a
    fresh token, which rotates the cluster credential on re-apply."""
    import os as _os
    import secrets as _secrets

    token = config.auth_token \
        or _os.environ.get("POLYAXON_TPU_AUTH_TOKEN") \
        or _secrets.token_hex(24)
    return {
        "apiVersion": "v1", "kind": "Secret",
        "metadata": _meta(config.auth_secret_name, config),
        "type": "Opaque",
        "stringData": {"token": token},
    }


def crd() -> Dict[str, Any]:
    """The Operation CRD the native operator reconciles."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "operations.core.polyaxon-tpu.io"},
        "spec": {
            "group": "core.polyaxon-tpu.io",
            "names": {"kind": "Operation", "plural": "operations",
                      "singular": "operation", "shortNames": ["op"]},
            "scope": "Namespaced",
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {"spec": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    }},
                }},
                "subresources": {"status": {}},
            }],
        },
    }


def rbac(config: DeploymentConfig) -> List[Dict[str, Any]]:
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": _meta(config.service_account, config)},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
         "metadata": _meta("polyaxon-tpu-role", config),
         "rules": [
             {"apiGroups": ["core.polyaxon-tpu.io"],
              "resources": ["operations", "operations/status"],
              "verbs": ["*"]},
             {"apiGroups": [""],
              "resources": ["pods", "pods/log", "services", "events",
                            "secrets", "configmaps"],
              "verbs": ["get", "list", "watch", "create", "delete",
                        "patch"]},
         ]},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
         "metadata": _meta("polyaxon-tpu-rolebinding", config),
         "subjects": [{"kind": "ServiceAccount",
                       "name": config.service_account,
                       "namespace": config.namespace}],
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "Role", "name": "polyaxon-tpu-role"}},
    ]


def control_plane(config: DeploymentConfig) -> List[Dict[str, Any]]:
    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": _meta("polyaxon-tpu-api", config),
        "spec": {
            "replicas": config.replicas_api,
            "selector": {"matchLabels":
                         {"app.kubernetes.io/name": "polyaxon-tpu-api"}},
            "template": {
                "metadata": {"labels":
                             {"app.kubernetes.io/name":
                              "polyaxon-tpu-api"},
                             # the control plane serves Prometheus
                             # text at /metrics (scheduler/api.py)
                             "annotations": {
                                 "prometheus.io/scrape": "true",
                                 "prometheus.io/path": "/metrics",
                                 "prometheus.io/port":
                                     str(config.api_port)}},
                "spec": {
                    "serviceAccountName": config.service_account,
                    "containers": [{
                        "name": "api",
                        "image": config.image,
                        "command": ["python", "-m", "polyaxon_tpu.cli",
                                    "server", "--host", "0.0.0.0",
                                    "--port", str(config.api_port)],
                        "ports": [{"containerPort": config.api_port}],
                        "env": _env_list(config),
                        "readinessProbe": {"httpGet": {
                            "path": "/api/v1/healthz",
                            "port": config.api_port}},
                    }],
                    "volumes": [],
                },
            },
        },
    }
    if config.artifacts_claim:
        deployment["spec"]["template"]["spec"]["volumes"].append({
            "name": "artifacts",
            "persistentVolumeClaim":
                {"claimName": config.artifacts_claim}})
        api_container = deployment["spec"]["template"]["spec"][
            "containers"][0]
        api_container["volumeMounts"] = [{"name": "artifacts",
                                          "mountPath": "/ptpu-artifacts"}]
        # The run store must live ON the claim, or API restarts lose
        # every run record/log.
        api_container["env"].append({"name": "POLYAXON_TPU_HOME",
                                     "value": "/ptpu-artifacts"})
    service = {
        "apiVersion": "v1", "kind": "Service",
        "metadata": _meta("polyaxon-tpu-api", config),
        "spec": {
            "selector": {"app.kubernetes.io/name": "polyaxon-tpu-api"},
            "ports": [{"port": config.api_port,
                       "targetPort": config.api_port}],
        },
    }
    return [deployment, service]


def agent(config: DeploymentConfig) -> List[Dict[str, Any]]:
    """The agent deployment, per transport.

    ``kube``: agent submits Operation CRs to the apiserver
    (``--backend kube``); the operator container reconciles them into
    real pods via ``--kube-api`` through a kubectl-proxy sidecar
    (the operator's HTTP client is plaintext; the proxy terminates TLS
    with the pod's service account).  RBAC for both is the Role below.

    ``manifest``: agent + operator share ONE pod so the manifest
    hand-off directory (agent writes CRs, operator reconciles them) is
    a single shared emptyDir — split pods would each get a private
    volume and the operator would never see the agent's manifests."""
    host = f"http://polyaxon-tpu-api.{config.namespace}:{config.api_port}"
    if config.transport == "kube":
        proxy = f"http://127.0.0.1:{config.kube_proxy_port}"
        containers = [
            {
                "name": "agent",
                "image": config.image,
                "command": ["python", "-m", "polyaxon_tpu.cli",
                            "agent", "--name", config.agent_name,
                            "--backend", "kube"],
                "env": _env_list(config, {
                    "POLYAXON_TPU_HOST": host,
                    "PTPU_K8S_NAMESPACE": config.namespace,
                }),
            },
            {
                "name": "operator",
                "image": config.operator_image,
                "command": ["/ptpu-operator",
                            "--kube-api", proxy,
                            "--namespace", config.namespace],
            },
            {
                "name": "kubectl-proxy",
                "image": "bitnami/kubectl:latest",
                "command": ["kubectl", "proxy",
                            f"--port={config.kube_proxy_port}",
                            "--address=127.0.0.1"],
            },
        ]
        pod_spec = {"serviceAccountName": config.service_account,
                    "containers": containers}
    else:
        pod_spec = {
            "serviceAccountName": config.service_account,
            "containers": [
                {
                    "name": "agent",
                    "image": config.image,
                    "command": ["python", "-m",
                                "polyaxon_tpu.cli",
                                "agent", "--name",
                                config.agent_name,
                                "--backend", "manifest",
                                "--cluster-dir", "/ptpu-cluster"],
                    "env": _env_list(config,
                                     {"POLYAXON_TPU_HOST": host}),
                    "volumeMounts": [{"name": "cluster",
                                      "mountPath":
                                      "/ptpu-cluster"}],
                },
                {
                    "name": "operator",
                    "image": config.operator_image,
                    "command": ["/ptpu-operator", "--cluster-dir",
                                "/ptpu-cluster"],
                    "volumeMounts": [{"name": "cluster",
                                      "mountPath":
                                      "/ptpu-cluster"}],
                },
            ],
            "volumes": [{"name": "cluster", "emptyDir": {}}],
        }
    return [{
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": _meta("polyaxon-tpu-agent", config),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels":
                         {"app.kubernetes.io/name": "polyaxon-tpu-agent"}},
            "template": {
                "metadata": {"labels":
                             {"app.kubernetes.io/name":
                              "polyaxon-tpu-agent"}},
                "spec": pod_spec,
            },
        },
    }]


def render_all(config: Optional[DeploymentConfig] = None
               ) -> List[Dict[str, Any]]:
    config = config or DeploymentConfig()
    manifests: List[Dict[str, Any]] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": config.namespace}},
        crd(),
        auth_secret(config),
    ]
    manifests += rbac(config)
    manifests += control_plane(config)
    manifests += agent(config)  # agent pod carries the operator sidecar
    return manifests
