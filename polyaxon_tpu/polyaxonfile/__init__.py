"""Polyaxonfile reading: YAML -> V1Operation / V1Component.

Parity with the reference's ``polyaxon/_polyaxonfile/`` (SURVEY.md 2.2 —
unverified path): multi-file merge, ``-P`` param overrides, presets,
``--patch`` run patches.
"""

from .reader import (
    OperationSpecification,
    check_polyaxonfile,
    get_op_from_files,
    read_polyaxonfile,
)
