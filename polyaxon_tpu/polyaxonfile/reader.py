"""Read, merge and normalize polyaxonfiles.

A polyaxonfile may contain:
  - ``kind: operation`` — an operation (optionally with inline component);
  - ``kind: component`` — a bare component (wrapped into an operation).

Multiple ``-f`` files deep-merge in order (later wins); ``-P name=value``
overrides params; ``--preset`` files apply with their declared patch
strategy (default post_merge).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import yaml

from ..flow import V1Component, V1Operation
from ..flow.base import patch_dict
from ..flow.io import params_from_dict


class PolyaxonfileError(ValueError):
    pass


def _load_file(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        raise PolyaxonfileError(f"Polyaxonfile not found: {path}")
    with open(path) as f:
        try:
            data = yaml.safe_load(f)
        except yaml.YAMLError as e:
            raise PolyaxonfileError(f"Invalid YAML in {path}: {e}") from e
    if not isinstance(data, dict):
        raise PolyaxonfileError(
            f"Polyaxonfile {path} must contain a mapping, got {type(data).__name__}"
        )
    return data


def _load(source: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    if isinstance(source, dict):
        return source
    # Only multi-line strings are treated as inline YAML; anything else is a
    # file path (so a typo'd path errors with "not found", not a parse error).
    if isinstance(source, str) and "\n" in source and not os.path.exists(source):
        data = yaml.safe_load(source)
        if not isinstance(data, dict):
            raise PolyaxonfileError("Inline polyaxonfile must be a mapping")
        return data
    return _load_file(source)


def _coerce_param_value(raw: str) -> Any:
    """CLI `-P key=value` values arrive as strings; YAML-parse scalars."""
    try:
        return yaml.safe_load(raw)
    except yaml.YAMLError:
        return raw


def read_polyaxonfile(
    sources: Union[str, Dict[str, Any], List[Union[str, Dict[str, Any]]]],
) -> Dict[str, Any]:
    """Deep-merge one or more YAML sources into a single spec dict."""
    if not isinstance(sources, list):
        sources = [sources]
    if not sources:
        raise PolyaxonfileError("No polyaxonfile provided")
    merged: Optional[Dict[str, Any]] = None
    for src in sources:
        data = _load(src)
        merged = data if merged is None else patch_dict(merged, data, "post_merge")
    return merged


def get_op_from_files(
    sources: Union[str, Dict[str, Any], List[Union[str, Dict[str, Any]]]],
    params: Optional[Dict[str, Any]] = None,
    presets: Optional[List[Union[str, Dict[str, Any]]]] = None,
    patches: Optional[List[Dict[str, Any]]] = None,
    name: Optional[str] = None,
) -> V1Operation:
    """Full CLI-equivalent pipeline: files + presets + -P params -> V1Operation."""
    import copy

    # Deep-copy so caller-supplied spec dicts are never mutated by merges
    # or -P writes (one dict may seed many operations).
    spec = copy.deepcopy(read_polyaxonfile(sources))
    kind = spec.get("kind")

    if kind == "component":
        component = V1Component.from_dict(spec)
        op_spec: Dict[str, Any] = {
            "kind": "operation",
            "component": spec,
            "name": name or component.name,
        }
    elif kind == "operation":
        op_spec = spec
        if name:
            op_spec["name"] = name
    else:
        raise PolyaxonfileError(
            f"Polyaxonfile kind must be 'operation' or 'component', got {kind!r}"
        )

    # Presets: operation-shaped fragments (isPreset: true) merged in.
    for preset in presets or []:
        pdata = _load(preset)
        pdata = dict(pdata)
        pdata.pop("isPreset", None)
        pdata.pop("is_preset", None)
        pdata.pop("kind", None)
        strategy = pdata.pop("patchStrategy", pdata.pop("patch_strategy", "post_merge"))
        op_spec = patch_dict(op_spec, pdata, strategy)

    # Explicit --patch fragments.
    for patch in patches or []:
        op_spec = patch_dict(op_spec, dict(patch), "post_merge")

    # -P overrides win over everything.
    if params:
        op_params = dict(op_spec.get("params") or {})
        for key, value in params.items():
            if isinstance(value, str):
                value = _coerce_param_value(value)
            op_params[key] = {"value": value}
        op_spec["params"] = op_params

    return V1Operation.from_dict(op_spec)


def check_polyaxonfile(
    sources,
    params: Optional[Dict[str, Any]] = None,
    presets=None,
    patches=None,
) -> V1Operation:
    """Validate a polyaxonfile; raises PolyaxonfileError on any problem."""
    try:
        op = get_op_from_files(sources, params=params, presets=presets,
                               patches=patches)
    except PolyaxonfileError:
        raise
    except Exception as e:
        raise PolyaxonfileError(str(e)) from e
    if op.has_component:
        op.component.validate_params(
            {k: p for k, p in (op.params or {}).items()},
            is_template=op.matrix is not None,
        )
    return op


class OperationSpecification:
    """Namespace mirror of the reference's spec-reading entrypoints."""

    read = staticmethod(get_op_from_files)
    check = staticmethod(check_polyaxonfile)
