"""polyaxon_tpu — a TPU-native ML orchestration framework.

A brand-new framework with the capability surface of the reference
(zchunhai/polyaxon — declarative polyaxonfile specs, compile, schedule,
run, track, tune, stream, recover), re-designed TPU-first:

- ``flow``:          declarative spec schemas (components, operations,
                     runtime kinds incl. TPUJob, matrix kinds).
- ``polyaxonfile``:  YAML reading/merging/param overrides.
- ``compiler``:      param/context resolution -> CompiledOperation.
- ``tracking``:      in-process experiment tracking (traceml-equivalent).
- ``client``:        run/project clients over the local store or API.
- ``runner``:        local + distributed executors, agent.
- ``scheduler``:     control plane: queue, DAG/matrix progression, streams.
- ``k8s``:           converter emitting TPU-slice manifests.
- ``parallel``:      JAX distributed runtime: mesh, DP/TP/PP/SP/CP/EP,
                     ring attention, Ulysses, ICI/DCN collectives.
- ``ops``:           Pallas/XLA kernels for hot ops.
- ``models``:        flagship model families (ResNet, BERT, GPT-2, ...).
- ``tune``:          hyperparameter search (grid/random/hyperband/bayes/...).

Unlike the reference — which delegates distributed compute to
NCCL/MPI/Kubeflow operators (SURVEY.md section 2.5/5.8) — this framework
owns the device mesh natively via jax.sharding/pjit/shard_map.
"""

__version__ = "0.1.0"

DIST = "polyaxon-tpu"
