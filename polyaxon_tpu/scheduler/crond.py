"""Schedule materialization: cron / interval / datetime kinds.

Parity: reference ``V1Schedule*`` semantics (SURVEY.md 2.4) — an
operation carrying ``schedule:`` becomes a *controller* run in status
``on_schedule``; at each fire time the service creates a child run
(queued, schedule stripped) until ``maxRuns``/``endAt`` exhausts the
schedule.  Pure-stdlib cron matcher; no external deps.
"""

from __future__ import annotations

import datetime as dt
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..client.store import FileRunStore
from ..lifecycle import V1Statuses

logger = logging.getLogger(__name__)

_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


class ScheduleError(ValueError):
    pass


def _parse_field(spec: str, lo: int, hi: int) -> set:
    values = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        for v in rng:
            if not lo <= v <= hi:
                raise ScheduleError(
                    f"cron field value {v} out of range [{lo},{hi}]")
            if (v - rng.start) % step == 0:
                values.add(v)
    return values


class Cron:
    """5-field cron expression (minute hour day-of-month month weekday)."""

    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise ScheduleError(
                f"cron expression needs 5 fields, got {expr!r}")
        self.minute, self.hour, self.dom, self.month, self.dow = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES))

    def matches(self, t: dt.datetime) -> bool:
        # cron weekday convention: Sunday=0; Python weekday(): Monday=0.
        cron_dow = (t.weekday() + 1) % 7
        return (t.minute in self.minute and t.hour in self.hour
                and t.day in self.dom and t.month in self.month
                and cron_dow in self.dow)

    def next_after(self, t: dt.datetime) -> dt.datetime:
        """First matching minute strictly after ``t`` (bounded scan)."""
        t = t.replace(second=0, microsecond=0) + dt.timedelta(minutes=1)
        for _ in range(366 * 24 * 60):
            if self.matches(t):
                return t
            t += dt.timedelta(minutes=1)
        raise ScheduleError("cron expression never fires within a year")


def _parse_when(value: Any) -> Optional[float]:
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    return dt.datetime.fromisoformat(str(value)).timestamp()


def next_fire_time(schedule: Dict[str, Any], after: float,
                   iteration: int) -> Optional[float]:
    """Epoch seconds of the next firing after ``after``; None = exhausted."""
    kind = schedule.get("kind")
    max_runs = schedule.get("maxRuns") or schedule.get("max_runs")
    if max_runs is not None and iteration >= int(max_runs):
        return None
    start_at = _parse_when(schedule.get("startAt")
                           or schedule.get("start_at"))
    end_at = _parse_when(schedule.get("endAt") or schedule.get("end_at"))

    if kind == "datetime":
        fire = _parse_when(schedule.get("startAt")
                           or schedule.get("start_at"))
        if fire is None:
            raise ScheduleError("datetime schedule needs startAt")
        return None if iteration >= 1 else fire

    if kind == "interval":
        freq = float(schedule.get("frequency"))
        base = start_at if start_at is not None else after
        fire = max(base, after) if iteration == 0 else after + freq
    elif kind == "cron":
        local = dt.datetime.fromtimestamp(max(after, start_at or 0))
        fire = Cron(schedule["cron"]).next_after(local).timestamp()
    else:
        raise ScheduleError(f"Unknown schedule kind {kind!r}")

    if end_at is not None and fire > end_at:
        return None
    return fire


class ScheduleService:
    """Background loop materializing scheduled operations into child runs
    and sweeping zombie runs (stale tracking heartbeats — SURVEY.md 5.3).

    ``zombie_threshold_s``: seconds without a heartbeat before a RUNNING
    run is failed (``POLYAXON_TPU_ZOMBIE_THRESHOLD`` env overrides;
    0 disables the sweep).
    """

    def __init__(self, store: FileRunStore, poll_interval: float = 1.0,
                 zombie_threshold_s: Optional[float] = None):
        import os

        self.store = store
        self.poll_interval = poll_interval
        if zombie_threshold_s is None:
            zombie_threshold_s = float(
                os.environ.get("POLYAXON_TPU_ZOMBIE_THRESHOLD", "300"))
        self.zombie_threshold_s = zombie_threshold_s
        # The sweep scans every run record; at a 1s poll interval that
        # would double the store scan each tick for a 300s-granularity
        # check.  Throttle it to a fraction of the threshold.
        self._sweep_interval = max(10.0, zombie_threshold_s / 10.0)
        self._last_sweep = 0.0
        self._plane = None
        self._stop = threading.Event()

    def stop(self):
        self._stop.set()

    def run_forever(self):
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.poll_interval)

    def tick(self, now: Optional[float] = None) -> List[str]:
        """Fire due schedules; returns uuids of created child runs."""
        now = now if now is not None else time.time()
        if self.zombie_threshold_s > 0 and \
                now - self._last_sweep >= self._sweep_interval:
            self._last_sweep = now
            if self._plane is None:
                from .api import ControlPlane

                self._plane = ControlPlane(self.store)
            try:
                self._plane.sweep_zombies(self.zombie_threshold_s,
                                          now=now)
            except Exception:  # the daemon must outlive a bad sweep
                logger.exception("zombie sweep failed")
        created: List[str] = []
        controllers = self.store.list_runs(
            query=f"status:{V1Statuses.ON_SCHEDULE}")
        for record in controllers:
            content = record.get("content") or {}
            schedule = content.get("schedule")
            if not schedule:
                continue
            meta = record.get("meta_info") or {}
            iteration = int(meta.get("schedule_iteration") or 0)
            next_at = meta.get("schedule_next_at")
            if next_at is None:
                next_at = next_fire_time(schedule, now, iteration)
                if next_at is None:
                    self.store.set_status(record["uuid"],
                                          V1Statuses.SUCCEEDED,
                                          reason="ScheduleExhausted",
                                          force=True)
                    continue
                self.store.update_run(record["uuid"], meta_info={
                    **meta, "schedule_next_at": next_at})
                continue
            if float(next_at) > now:
                continue
            # Fire: child op = controller content minus the schedule.
            child_content = dict(content)
            child_content.pop("schedule", None)
            child = self.store.create_run(
                name=f"{record.get('name')}-{iteration}",
                project=record.get("project") or "default",
                content=child_content,
                kind=record.get("kind"),
                pipeline=record["uuid"],
                meta_info={"schedule_iteration": iteration},
                # inherit queue routing/priority from the controller
                queue=record.get("queue"),
                priority=record.get("priority") or 0,
            )
            self.store.set_status(child["uuid"], V1Statuses.QUEUED,
                                  reason="ScheduleFire")
            created.append(child["uuid"])
            iteration += 1
            upcoming = next_fire_time(schedule, float(next_at), iteration)
            new_meta = {**meta, "schedule_iteration": iteration,
                        "schedule_next_at": upcoming}
            self.store.update_run(record["uuid"], meta_info=new_meta)
            if upcoming is None:
                self.store.set_status(record["uuid"], V1Statuses.SUCCEEDED,
                                      reason="ScheduleExhausted", force=True)
        return created
