"""Control-plane API service (SURVEY.md 2.8 / §7 step 8).

The reference splits this across a Django API, DB, orchestration and a
streams service (``haupt``); here it is ONE stdlib-threaded HTTP process
over the ``FileRunStore`` — runs DB, scheduling queue, status plane, and
log/event streaming in ~300 lines.  ``client.ApiRunStore`` is the
matching client; the agent claims queued work via ``/agent/claim``.

Endpoints (all under ``/api/v1``):

    POST   /runs                         create
    GET    /runs?project&query&sort&...  list (query DSL applies)
    GET    /runs/<u>                     fetch
    PATCH  /runs/<u>                     update fields
    DELETE /runs/<u>                     delete
    POST   /runs/<u>/statuses            transition {status, reason, ...}
    GET    /runs/<u>/statuses            condition history
    POST   /runs/<u>/events              append event batch
    GET    /runs/<u>/events?kind&name&offset
    GET    /runs/<u>/events/names?kind
    GET    /runs/<u>/metrics/last
    POST   /runs/<u>/logs                append {text, replica}
    GET    /runs/<u>/logs?replica&tail&offset  (offset -> incremental read)
    POST   /runs/<u>/lineage             add artifact lineage record
    GET    /runs/<u>/lineage
    POST   /agent/claim                  {agent, queues?} -> next queued run
    GET    /healthz
    GET    /metrics                      Prometheus text (runs by
                                         status, queue depth, agents);
                                         also served at the ROOT path
                                         /metrics for scrapers
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..client.store import FileRunStore, StoreError
from ..lifecycle import V1Statuses, is_done as _is_done_status


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class ControlPlane:
    """Request-independent core: store + queue semantics.

    Kept separate from the HTTP plumbing so the agent can embed it
    in-process (single-box deployments) and tests can drive it directly.
    """

    def __init__(self, store: Optional[FileRunStore] = None,
                 auth_token: Optional[str] = None):
        self.store = store or FileRunStore()
        self.auth_token = auth_token  # None = open (single-user/local)
        self._claim_lock = threading.Lock()

    # -- observability ---------------------------------------------------

    _METRICS_TTL_S = 10.0

    def metrics_text(self) -> str:
        """Prometheus text exposition of control-plane state: runs by
        status, queue depth per queue, claimed-agent count (SURVEY
        §5.5 — the scrape surface an in-cluster deployment pairs with
        the model server's /metrics).  Served WITHOUT auth (aggregate
        counts only — see the dispatch comment).

        The snapshot is TTL-cached: list_runs() re-reads every run's
        meta file from disk, and a 15s scrape interval against a
        long-lived store would otherwise turn /metrics into recurring
        full-store I/O growing with run history."""
        import time as _time
        from collections import Counter

        cached = getattr(self, "_metrics_cache", None)
        if cached and _time.monotonic() - cached[0] < self._METRICS_TTL_S:
            return cached[1]
        runs = self.store.list_runs()
        by_status = Counter((r.get("status") or "unknown")
                            for r in runs)
        queued_by_queue = Counter(
            (r.get("queue") or "default") for r in runs
            if r.get("status") == V1Statuses.QUEUED)
        agents = {r.get("agent") for r in runs
                  if r.get("agent") and not _is_done_status(
                      r.get("status"))}
        def esc(v: str) -> str:
            # Prometheus label-value escaping: a user-supplied queue
            # name with a quote/newline must not invalidate the WHOLE
            # scrape.
            return (str(v).replace("\\", "\\\\")
                    .replace('"', '\\"').replace("\n", "\\n"))

        lines = ["# TYPE ptpu_runs gauge"]
        for status, n in sorted(by_status.items()):
            lines.append(f'ptpu_runs{{status="{esc(status)}"}} {n}')
        lines.append("# TYPE ptpu_queue_depth gauge")
        for queue, n in sorted(queued_by_queue.items()):
            lines.append(
                f'ptpu_queue_depth{{queue="{esc(queue)}"}} {n}')
        lines += ["# TYPE ptpu_active_agents gauge",
                  f"ptpu_active_agents {len(agents)}"]
        text = "\n".join(lines) + "\n"
        self._metrics_cache = (_time.monotonic(), text)
        return text

    # -- queue ----------------------------------------------------------

    def claim(self, agent: str,
              queues: Optional[List[str]] = None) -> Optional[Dict[str, Any]]:
        """Atomically hand the oldest queued run to an agent."""
        with self._claim_lock:
            queued = self.store.list_runs(query=f"status:{V1Statuses.QUEUED}",
                                          sort="created_at")
            # Priority first (higher wins), FIFO within a priority.
            # Defensive key: a PATCHed non-numeric priority on one record
            # must not poison claiming for every agent.
            def neg_priority(record):
                try:
                    return -int(record.get("priority") or 0)
                except (TypeError, ValueError):
                    return 0

            queued.sort(key=neg_priority)
            for record in queued:
                if queues and record.get("queue") not in queues:
                    continue
                ok = self.store.set_status(
                    record["uuid"], V1Statuses.SCHEDULED,
                    reason="AgentClaim", message=agent)
                if ok:
                    self.store.update_run(record["uuid"], agent=agent)
                    return self.store.get_run(record["uuid"])
        return None

    # -- failure detection (SURVEY.md 5.3) -------------------------------

    def sweep_zombies(self, threshold_s: float = 300.0,
                      now: Optional[float] = None) -> List[str]:
        """Fail RUNNING runs whose tracking heartbeat went stale.

        Second line of defense behind the operator's pod supervision:
        catches trainers that died without the pod failing (network
        partition from the store, wedged accelerator runtime, kill -9 of
        the python process inside a living pod).  Runs that never sent a
        heartbeat (no tracking — services, bare shell jobs) are NEVER
        swept.  Returns the uuids marked failed.
        """
        import time as _time

        now = now if now is not None else _time.time()
        swept: List[str] = []
        running = self.store.list_runs(
            query=f"status:{V1Statuses.RUNNING}")
        for record in running:
            try:
                beat = self.store.heartbeat_at(record["uuid"])
                if beat is None:
                    continue
                age = now - beat
                if age <= threshold_s:
                    continue
                # The heartbeat may belong to a PREVIOUS attempt
                # (restart/resume reuses the uuid): only sweep when this
                # attempt's RUNNING transition is itself older than the
                # threshold and predates no fresher beat.
                running_since = None
                for cond in reversed(self.store.get_statuses(
                        record["uuid"])):
                    if cond.type == V1Statuses.RUNNING:
                        running_since = cond.last_transition_time
                        break
                # 1s slack: file mtimes are coarser than time.time(), so
                # a beat touched right after the transition can stat
                # marginally older than the condition timestamp.
                if running_since is not None and (
                        now - running_since <= threshold_s
                        or beat < running_since - 1.0):
                    continue
                # No force: if the run reached a terminal status between
                # the list and this call, can_transition rejects the
                # overwrite (RUNNING -> FAILED itself is legal).
                ok = self.store.set_status(
                    record["uuid"], V1Statuses.FAILED,
                    reason="ZombieDetection",
                    message=f"no heartbeat for {int(age)}s "
                            f"(threshold {int(threshold_s)}s)")
                if ok:
                    swept.append(record["uuid"])
            except Exception:
                # A deleted/corrupt run must not end the sweep (or
                # the daemon calling it) — but a sweep that skips
                # silently would also hide a broken store forever.
                import logging

                logging.getLogger(__name__).debug(
                    "zombie sweep skipped a run", exc_info=True)
                continue
        return swept

    # -- streams --------------------------------------------------------

    def read_logs_from(self, run_uuid: str, replica: Optional[str],
                       offset: int) -> Dict[str, Any]:
        """Incremental log read: byte offset in, new text + offset out.

        Offsets are stable only within ONE replica file; with several
        replicas and no replica named, the aggregated text shifts as
        earlier files grow, so fall back to full snapshots (offset 0).
        """
        if replica is None:
            import os

            logs_dir = os.path.join(self.store.run_path(run_uuid), "logs")
            files = sorted(os.listdir(logs_dir)) if os.path.isdir(logs_dir) \
                else []
            if len(files) == 1:
                replica = files[0].removesuffix(".log")
            elif len(files) > 1:
                return {"logs": self.store.read_logs(run_uuid),
                        "offset": 0}
        text = self.store.read_logs(run_uuid, replica=replica)
        blob = text.encode()
        chunk = blob[offset:] if 0 <= offset <= len(blob) else blob
        return {"logs": chunk.decode(errors="replace"),
                "offset": len(blob)}

    def read_logs_multi(self, run_uuid: str,
                        offsets: Dict[str, int]) -> Dict[str, Any]:
        """Per-replica incremental reads — the `--follow` protocol.

        ``offsets``: replica -> byte offset already delivered.  Returns
        {"replicas": {replica: {"logs": new_text, "offset": new_off}}}.
        Offsets are per-file, so multi-replica streams never shift.
        """
        import os

        logs_dir = os.path.join(self.store.run_path(run_uuid), "logs")
        out: Dict[str, Any] = {}
        if os.path.isdir(logs_dir):
            for fname in sorted(os.listdir(logs_dir)):
                if not fname.endswith(".log"):
                    continue
                replica = fname[:-4]
                offset = int(offsets.get(replica, 0))
                path = os.path.join(logs_dir, fname)
                try:
                    size = os.path.getsize(path)
                    if offset > size:
                        offset = 0  # truncated/rotated: restart
                    with open(path, "rb") as f:
                        f.seek(offset)
                        chunk = f.read()
                except OSError:
                    continue
                out[replica] = {"logs": chunk.decode(errors="replace"),
                                "offset": offset + len(chunk)}
        return {"replicas": out}


def _json_response(handler: BaseHTTPRequestHandler, code: int,
                   payload: Any) -> None:
    blob = json.dumps(payload, default=str).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(blob)))
    handler.end_headers()
    handler.wfile.write(blob)


_ROUTES: List[Tuple[str, re.Pattern, str]] = [
    ("POST", re.compile(r"^/runs$"), "create_run"),
    ("GET", re.compile(r"^/runs$"), "list_runs"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)$"), "get_run"),
    ("PATCH", re.compile(r"^/runs/(?P<u>[^/]+)$"), "update_run"),
    ("DELETE", re.compile(r"^/runs/(?P<u>[^/]+)$"), "delete_run"),
    ("POST", re.compile(r"^/runs/(?P<u>[^/]+)/statuses$"), "set_status"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/statuses$"), "get_statuses"),
    ("POST", re.compile(r"^/runs/(?P<u>[^/]+)/events$"), "append_events"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/events$"), "read_events"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/events/names$"), "list_events"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/metrics/last$"), "last_metrics"),
    ("POST", re.compile(r"^/runs/(?P<u>[^/]+)/logs$"), "append_log"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/logs$"), "read_logs"),
    ("POST", re.compile(r"^/runs/(?P<u>[^/]+)/lineage$"), "add_lineage"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/lineage$"), "get_lineage"),
    ("POST", re.compile(r"^/runs/(?P<u>[^/]+)/heartbeat$"),
     "touch_heartbeat"),
    ("GET", re.compile(r"^/runs/(?P<u>[^/]+)/heartbeat$"),
     "get_heartbeat"),
    ("POST", re.compile(r"^/agent/claim$"), "agent_claim"),
    ("GET", re.compile(r"^/healthz$"), "healthz"),
]


class _Handler(BaseHTTPRequestHandler):
    plane: ControlPlane  # set by make_server

    # -- dispatch -------------------------------------------------------

    def _authorized(self) -> bool:
        """ONE bearer-token check for every protected route (API and
        /metrics) — auth fixes must not diverge between them."""
        if not self.plane.auth_token:
            return True
        import hmac

        supplied = (self.headers.get("Authorization") or "")
        return hmac.compare_digest(supplied.removeprefix("Bearer "),
                                   self.plane.auth_token)

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        if method == "GET" and parsed.path in ("/", "/ui"):
            # Static, data-free page (its JS supplies the bearer token
            # for the actual API calls) — safe to serve unauthenticated.
            from .dashboard import DASHBOARD_HTML

            blob = DASHBOARD_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)
            return
        if method == "GET" and parsed.path in ("/metrics",
                                               "/api/v1/metrics"):
            # Unauthenticated like /healthz: annotation-driven
            # Prometheus scrapes send no Authorization header, and the
            # rendered in-cluster deployment ALWAYS sets a token — an
            # auth-gated /metrics would 401 every scrape of the
            # endpoint its own annotations advertise.  Exposes only
            # aggregate gauges (counts), no run content.
            blob = self.plane.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)
            return
        if not parsed.path.startswith("/api/v1"):
            return _json_response(self, 404, {"error": "not found"})
        path = parsed.path[len("/api/v1"):] or "/"
        if path != "/healthz" and not self._authorized():
            return _json_response(self, 401, {"error": "unauthorized"})
        params = {k: v[0] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
        body: Dict[str, Any] = {}
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                return _json_response(self, 400, {"error": "bad json"})
        for verb, pattern, name in _ROUTES:
            if verb != method:
                continue
            m = pattern.match(path)
            if m:
                try:
                    result = getattr(self, "_h_" + name)(
                        body, params, **m.groupdict())
                except ApiError as e:
                    return _json_response(self, e.code,
                                          {"error": e.message})
                except (StoreError, FileNotFoundError) as e:
                    return _json_response(self, 404, {"error": str(e)})
                except (ValueError, TypeError, KeyError) as e:
                    # Body-driven **kwargs: bad/missing fields surface as
                    # a 400, never a dropped connection.
                    return _json_response(self, 400, {"error": repr(e)})
                return _json_response(self, 200, result)
        return _json_response(self, 404, {"error": f"no route {path}"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PATCH(self):
        self._dispatch("PATCH")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def log_message(self, fmt, *args):  # quiet by default
        pass

    # -- handlers -------------------------------------------------------

    def _h_healthz(self, body, params):
        return {"status": "ok"}

    _CREATE_FIELDS = frozenset({
        "name", "project", "description", "tags", "content", "kind",
        "pipeline", "meta_info", "run_uuid", "managed_by",
        "queue", "priority",
    })

    def _h_create_run(self, body, params):
        # Whitelist kwargs: the store signature is not a network contract,
        # and run_uuid is additionally validated as a safe path id inside
        # the store (ADVICE r1: unauthenticated path traversal).
        unknown = set(body) - self._CREATE_FIELDS
        if unknown:
            raise ApiError(400, f"unknown fields: {sorted(unknown)}")
        return self.plane.store.create_run(**body)

    def _h_list_runs(self, body, params):
        limit = params.get("limit")
        runs = self._list_runs_core(params, limit)
        if params.get("metrics"):
            # Inline last-metrics per run: ONE request for the
            # dashboard instead of an N+1 fetch fan-out.
            for r in runs:
                try:
                    r["last_metrics"] = \
                        self.plane.store.last_metrics(r["uuid"])
                except (StoreError, OSError):
                    r["last_metrics"] = {}
        return runs

    def _list_runs_core(self, params, limit):
        return self.plane.store.list_runs(
            project=params.get("project"),
            pipeline=params.get("pipeline"),
            query=params.get("query"),
            sort=params.get("sort"),
            limit=int(limit) if limit else None,
            offset=int(params.get("offset") or 0),
        )

    def _h_get_run(self, body, params, u):
        return self.plane.store.get_run(u)

    def _h_update_run(self, body, params, u):
        return self.plane.store.update_run(u, **body)

    def _h_delete_run(self, body, params, u):
        self.plane.store.delete_run(u)
        return {"ok": True}

    def _h_set_status(self, body, params, u):
        ok = self.plane.store.set_status(
            u, body.get("status"), reason=body.get("reason"),
            message=body.get("message"), force=bool(body.get("force")))
        return {"ok": ok}

    def _h_get_statuses(self, body, params, u):
        return [c.to_dict() for c in self.plane.store.get_statuses(u)]

    def _h_append_events(self, body, params, u):
        self.plane.store.append_events(u, body["kind"], body["name"],
                                       body.get("events") or [])
        return {"ok": True}

    def _h_read_events(self, body, params, u):
        return self.plane.store.read_events(
            u, params.get("kind"), params.get("name"),
            offset=int(params.get("offset") or 0))

    def _h_list_events(self, body, params, u):
        return self.plane.store.list_events(u, kind=params.get("kind"))

    def _h_last_metrics(self, body, params, u):
        return self.plane.store.last_metrics(u)

    def _h_touch_heartbeat(self, body, params, u):
        self.plane.store.touch_heartbeat(u)
        return {"ok": True}

    def _h_get_heartbeat(self, body, params, u):
        return {"heartbeat_at": self.plane.store.heartbeat_at(u)}

    def _h_append_log(self, body, params, u):
        self.plane.store.append_log(u, body.get("text", ""),
                                    replica=body.get("replica") or "main")
        return {"ok": True}

    def _h_read_logs(self, body, params, u):
        if "offsets" in params:
            offsets = json.loads(params["offsets"]) or {}
            return self.plane.read_logs_multi(u, offsets)
        if "offset" in params:
            return self.plane.read_logs_from(
                u, params.get("replica"), int(params["offset"]))
        tail = params.get("tail")
        return {"logs": self.plane.store.read_logs(
            u, replica=params.get("replica"),
            tail=int(tail) if tail else None)}

    def _h_add_lineage(self, body, params, u):
        self.plane.store.add_lineage(u, body)
        return {"ok": True}

    def _h_get_lineage(self, body, params, u):
        return self.plane.store.get_lineage(u)

    def _h_agent_claim(self, body, params):
        record = self.plane.claim(body.get("agent") or "agent",
                                  queues=body.get("queues"))
        return record or {}


def make_server(host: str = "127.0.0.1", port: int = 8000,
                store: Optional[FileRunStore] = None,
                plane: Optional[ControlPlane] = None) -> ThreadingHTTPServer:
    plane = plane or ControlPlane(store)
    handler = type("Handler", (_Handler,), {"plane": plane})
    server = ThreadingHTTPServer((host, port), handler)
    server.plane = plane  # type: ignore[attr-defined]
    return server


def serve_forever(host: str = "127.0.0.1", port: int = 8000,
                  store: Optional[FileRunStore] = None) -> None:
    server = make_server(host, port, store)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
