"""Control plane: API service, scheduling queue, schedules, streams.

The reference's L4 (``haupt``: API + DB + orchestration + streams —
SURVEY.md 2.8) collapsed into one stdlib-HTTP process over the file
store, plus a schedule-materializer thread.  Agents (``runner.agent``)
poll ``/agent/claim``; clients speak ``client.ApiRunStore``.
"""

from .api import ApiError, ControlPlane, make_server, serve_forever
from .crond import Cron, ScheduleError, ScheduleService, next_fire_time

__all__ = [
    "ApiError",
    "ControlPlane",
    "Cron",
    "ScheduleError",
    "ScheduleService",
    "make_server",
    "next_fire_time",
    "serve_forever",
]
