"""Read-only web dashboard served by the control plane.

The reference ships a full web UI; this is the compact equivalent for
the single-process control plane: one self-contained HTML page (no
assets, no build step) that polls the existing JSON API — status
tiles, a runs table, and a per-run detail pane (status history, last
metrics, log tail).  Served at ``GET /`` and ``GET /ui`` WITHOUT auth
(the page is static and data-free); its JavaScript calls ``/api/v1``
with the bearer token the operator pastes into the token field
(persisted in localStorage), so a token-gated deployment stays gated.

Design notes (dataviz method): the data's job here is identity +
state, so the form is a table plus stat tiles — not charts; status is
never color-alone (each state renders a dot AND its word); all text
wears neutral ink.
"""

DASHBOARD_HTML = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<title>polyaxon-tpu — runs</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root {
  --ink: #1a1a1a; --ink2: #555; --ink3: #888;
  --surface: #fafaf8; --card: #ffffff; --line: #e4e2dd;
  --ok: #1a7f37; --warn: #b08800; --bad: #b42318; --run: #175cd3;
}
@media (prefers-color-scheme: dark) {
  :root { --ink: #ececec; --ink2: #b5b5b5; --ink3: #8a8a8a;
          --surface: #161614; --card: #201f1d; --line: #3a3834;
          --ok: #4cc38a; --warn: #d4b106; --bad: #f97066;
          --run: #84adff; }
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--surface); color: var(--ink);
       font: 14px/1.45 system-ui, sans-serif; }
header { display: flex; align-items: baseline; gap: 16px;
         padding: 14px 20px; border-bottom: 1px solid var(--line); }
header h1 { font-size: 16px; margin: 0; }
header .sub { color: var(--ink3); font-size: 12px; }
header input { margin-left: auto; width: 220px; padding: 4px 8px;
               border: 1px solid var(--line); border-radius: 6px;
               background: var(--card); color: var(--ink); }
main { padding: 16px 20px; max-width: 1100px; margin: 0 auto; }
.tiles { display: flex; gap: 12px; flex-wrap: wrap; margin: 0 0 16px; }
.tile { background: var(--card); border: 1px solid var(--line);
        border-radius: 8px; padding: 10px 16px; min-width: 110px; }
.tile .n { font-size: 22px; font-weight: 600; }
.tile .k { color: var(--ink2); font-size: 12px; }
table { width: 100%; border-collapse: collapse; background: var(--card);
        border: 1px solid var(--line); border-radius: 8px;
        overflow: hidden; }
th { text-align: left; color: var(--ink2); font-weight: 500;
     font-size: 12px; padding: 8px 12px;
     border-bottom: 1px solid var(--line); }
td { padding: 7px 12px; border-bottom: 1px solid var(--line);
     font-variant-numeric: tabular-nums; }
tr:last-child td { border-bottom: 0; }
tr.row:hover { background: color-mix(in oklab, var(--card) 92%,
               var(--ink) 8%); cursor: pointer; }
.status { white-space: nowrap; }
.dot { display: inline-block; width: 8px; height: 8px;
       border-radius: 50%; margin-right: 6px; }
.s-succeeded .dot { background: var(--ok); }
.s-running .dot, .s-compiled .dot { background: var(--run); }
.s-failed .dot, .s-upstream_failed .dot { background: var(--bad); }
.s-stopped .dot, .s-skipped .dot { background: var(--ink3); }
.s-queued .dot, .s-created .dot, .s-scheduled .dot,
.s-warning .dot { background: var(--warn); }
.muted { color: var(--ink3); }
#detail { margin-top: 16px; background: var(--card);
          border: 1px solid var(--line); border-radius: 8px;
          padding: 14px 16px; display: none; }
#detail h2 { font-size: 14px; margin: 0 0 8px; }
#detail pre { background: var(--surface); border: 1px solid var(--line);
              border-radius: 6px; padding: 10px; overflow: auto;
              max-height: 260px; font-size: 12px; }
#err { color: var(--bad); font-size: 12px; padding: 8px 0; }
</style></head><body>
<header>
  <h1>polyaxon-tpu</h1>
  <span class="sub" id="meta">runs</span>
  <input id="token" type="password"
         placeholder="API token (blank if open)">
</header>
<main>
  <div id="err"></div>
  <div class="tiles" id="tiles"></div>
  <table id="runs"><thead><tr>
    <th>run</th><th>name</th><th>status</th><th>queue</th>
    <th>kind</th><th>metrics</th>
  </tr></thead><tbody></tbody></table>
  <div id="detail"></div>
</main>
<script>
"use strict";
const $ = (s) => document.querySelector(s);
// EVERY API-sourced string goes through esc() before touching
// innerHTML: run names/reasons/messages are arbitrary user input and
// the bearer token lives in localStorage (stored-XSS target).
const esc = (x) => String(x ?? "").replace(/[&<>"']/g, c => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;",
  "'": "&#39;"}[c]));
const tokenEl = $("#token");
tokenEl.value = localStorage.getItem("ptpu-token") || "";
tokenEl.addEventListener("change", () => {
  localStorage.setItem("ptpu-token", tokenEl.value); refresh();
});
async function api(path) {
  const headers = {};
  if (tokenEl.value) headers["Authorization"] = "Bearer " + tokenEl.value;
  const r = await fetch("/api/v1" + path, {headers});
  if (!r.ok) throw new Error(path + " -> HTTP " + r.status);
  return r.json();
}

function statusCell(s) {
  s = /^[a-z_]+$/.test(s || "") ? s : "created";
  return `<span class="status s-${s}"><span class="dot"></span>${s}</span>`;
}

function fmtTime(t) {
  if (!t) return "";
  const d = typeof t === "number" ? new Date(t * 1000) : new Date(t);
  return isNaN(d) ? esc(t) : d.toISOString().replace("T", " ").slice(0, 19);
}

function fmtMetrics(m) {
  const keys = Object.keys(m || {}).filter(
    k => !k.startsWith("_") && m[k] !== null && m[k] !== undefined);
  return keys.slice(0, 3).map(k => {
    let v = m[k]; if (typeof v === "number" && !Number.isInteger(v))
      v = v.toPrecision(4);
    return `${esc(k)}=${esc(v)}`;
  }).join("  ") || "—";
}

async function refresh() {
  try {
    const runs = await api("/runs?sort=-created_at&limit=100&metrics=1");
    $("#err").textContent = "";
    const counts = {};
    for (const r of runs) {
      const s = r.status || "created";
      counts[s] = (counts[s] || 0) + 1;
    }
    $("#tiles").innerHTML = Object.entries(counts).map(([s, n]) =>
      `<div class="tile"><div class="n">${Number(n)}</div>
       <div class="k">${statusCell(s)}</div></div>`).join("") ||
      '<div class="tile"><div class="n">0</div><div class="k">runs' +
      '</div></div>';
    $("#meta").textContent = runs.length + " runs";
    const rows = runs.map((r, i) =>
      `<tr class="row" data-u="${esc(r.uuid)}">
        <td class="muted">${esc((r.uuid || "").slice(0, 8))}</td>
        <td>${esc(r.name)}</td><td>${statusCell(r.status)}</td>
        <td>${esc(r.queue || "default")}</td>
        <td class="muted">${esc(r.kind)}</td>
        <td>${fmtMetrics(r.last_metrics)}</td>
      </tr>`);
    $("#runs tbody").innerHTML = rows.join("") ||
      '<tr><td colspan="6" class="muted">no runs yet</td></tr>';
    for (const tr of document.querySelectorAll("tr.row"))
      tr.addEventListener("click", () => showDetail(tr.dataset.u));
  } catch (e) { $("#err").textContent = String(e); }
}

async function showDetail(u) {
  const el = $("#detail"); el.style.display = "block";
  el.innerHTML = `<h2>${esc(u)}</h2><p class="muted">loading…</p>`;
  try {
    const [statuses, logs] = await Promise.all([
      api(`/runs/${encodeURIComponent(u)}/statuses`),
      // offsets={} selects the per-replica incremental form.
      api(`/runs/${encodeURIComponent(u)}/logs?offsets=%7B%7D`)
        .catch(() => ({replicas: {}})),
    ]);
    const hist = statuses.map(c =>
      `<tr><td>${statusCell(c.type)}</td>
       <td class="muted">${esc(c.reason)}</td>
       <td>${esc(c.message)}</td>
       <td class="muted">${fmtTime(c.last_transition_time)}</td>
      </tr>`).join("");
    let logText = "";
    for (const [rep, blob] of Object.entries(logs.replicas || {}))
      logText += `--- ${rep} ---\\n` +
        (blob.logs || "").split("\\n").slice(-40).join("\\n") + "\\n";
    el.innerHTML = `<h2>${esc(u)}</h2>
      <table><thead><tr><th>status</th><th>reason</th><th>message</th>
      <th>at</th></tr></thead><tbody>${hist}</tbody></table>
      <h2 style="margin-top:12px">logs (tail)</h2>
      <pre>${esc(logText) || "(no logs)"}</pre>`;
  } catch (e) {
    el.innerHTML = `<h2>${esc(u)}</h2><div id="err">${esc(e)}</div>`;
  }
}

// Self-re-arming + an inflight guard: the next cycle starts 5 s
// after the previous one FINISHES, hidden tabs stop polling, and the
// visibility kick can never overlap a running refresh.
let inflight = false;
async function refreshOnce() {
  if (inflight) return;
  inflight = true;
  try { await refresh(); } finally { inflight = false; }
}
(async function loop() {
  if (!document.hidden) await refreshOnce();
  setTimeout(loop, 5000);
})();
document.addEventListener("visibilitychange", () => {
  if (!document.hidden) refreshOnce();
});
</script></body></html>
"""
