"""Native model server: the zoo's decode stack behind HTTP.

The reference's serving story is `V1Service` — it schedules an opaque
user container and port-forwards to it (SURVEY.md §2.4); the model
server inside is the user's problem.  Here the framework owns the
decode loop, so it ships the server too: one process, stdlib HTTP
(same no-dependency stance as the control plane), jit-compiled
generate with a shape-bucketed compile cache.

Endpoints:

- ``GET  /healthz``  -> ``{"status": "ok", ...}`` (readiness; also the
  operator's gang-health convention)
- ``GET  /info``     -> model name, config summary, quantization flags
- ``POST /prefill``  -> register a prompt (prefix) in the PREFIX
  CACHE: its KV prefill is stored on device (LRU, ``prefix_cache``
  entries) and later /generate requests whose prompt starts with it
  skip that prefill — the system-prompt serving win.  Hits extend and
  re-store, so growing sessions stay warm.  Exact by the
  prefill/continue split contract (models/generate.py).
- ``POST /generate`` -> ``{"prompt": [ids] | [[ids], ...],
  "max_new_tokens": N, "temperature": t, "top_k": k, "top_p": p,
  "eos_id": e, "num_beams": B, "speculative": bool, "spec_k": K,
  "seed": s, "prefill_chunk": C}`` -> tokens + timing (speculative
  needs a server-side draft model; greedy by default, and with
  temperature/top_k/top_p it runs rejection speculative sampling —
  exact target-distribution samples for any draft)

Shape discipline: each distinct (batch, prompt_len, max_new_tokens,
decode-mode) compiles once and is cached.  Prompts are NOT padded:
the zoo's decode path has no attention-mask input, so left-padding
would let real tokens attend to pad positions (silently wrong
output).  Clients with ragged traffic should bucket prompt lengths
themselves; every row in one request must share a length.

Concurrency: one chip means device work is serialized, but the server
does NOT serialize whole requests (VERDICT r4 weak/missing #4).
Greedy requests that share (prompt_len, eos, prefill_chunk) are
COALESCED — max_new_tokens may differ: the merged batch decodes to
the longest request's length and each response is sliced back to its
own.  Whoever acquires the device lock drains every compatible queued
request into one merged batch (batch-dim padded to a power-of-two
bucket so varied client counts reuse one compiled program), runs a
single jitted call, and hands each request its slice.  Merging is
exact — decode rows never interact across the batch dimension, and
eos-frozen rows emit eos past their budget (truncated by the slice) —
so a coalesced response is bit-identical to a solo one.
Sampled/beam/speculative requests keep the solo path (a shared PRNG
key or beam schedule would change their outputs if merged).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _int_param(v):
    """int() that refuses booleans: int(True) == 1 would silently
    accept {"num_beams": true} / {"prefill_chunk": true}."""
    if isinstance(v, bool):
        raise ValueError("expected an integer, got a boolean")
    return int(v)


def _parse_prompt_rows(req, max_batch: int):
    """Shared /generate + /prefill prompt validation: returns the
    row-wrapped token lists (one shared length, ints-not-bools,
    batch-capped)."""
    if not isinstance(req, dict):
        raise ValueError("request body must be a JSON object")
    rows = req.get("prompt")
    if rows is None:
        raise ValueError("missing 'prompt'")
    if not isinstance(rows, list):
        raise ValueError("'prompt' must be a list of token ids "
                         "or a list of rows")
    if rows and not isinstance(rows[0], list):
        rows = [rows]
    if not rows or not rows[0]:
        raise ValueError("prompt must contain at least one token")
    if len(rows) > max_batch:
        raise ValueError(f"batch {len(rows)} exceeds max_batch "
                         f"{max_batch}")
    if len({len(r) for r in rows}) != 1:
        # No silent padding: the decode path has no attention
        # mask, so padded positions would be attended to.
        raise ValueError(
            "all prompt rows must share one length (the decode "
            "path has no pad mask; bucket lengths client-side)")
    if any(not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in r) for r in rows):
        # bool is an int subclass: [true, false] must not silently
        # decode as tokens [1, 0].
        raise ValueError("prompt rows must be integer token ids")
    return rows


class _Pending:
    """One coalescible request waiting for a leader to execute it."""

    __slots__ = ("toks", "new", "event", "result", "error")

    def __init__(self, toks: np.ndarray, new: int):
        self.toks = toks          # [rows, p_len] int32
        self.new = new            # this request's max_new_tokens
        self.event = threading.Event()
        self.result = None        # [rows, p_len + new] when done
        self.error: Optional[BaseException] = None


def _batch_bucket(n: int, cap: int) -> int:
    """Next power-of-two >= n, capped: merged batches land on a handful
    of compiled shapes instead of one per client-count."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class ModelServer:
    """Wraps one model + params; owns the compile cache and the lock
    serializing device work (one chip — concurrent requests coalesce,
    see module docstring)."""

    def __init__(self, model, variables, *, model_name: str = "model",
                 max_batch: int = 8, coalesce: bool = True,
                 prefix_cache: int = 4,
                 draft_model=None, draft_variables=None,
                 info: Optional[Dict[str, Any]] = None):
        self.model = model
        self.variables = variables
        # coalesce=False serializes greedy requests like any other —
        # the A/B baseline for benchmarks/bench_serving_load.py.
        self.coalesce = bool(coalesce)
        # Optional speculative-decoding draft: requests opt in with
        # {"speculative": true}; greedy by default (output identical
        # to plain greedy decode), rejection-sampled with temperature
        # (models/generate.generate_speculative).
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        self.model_name = model_name
        self.max_batch = int(max_batch)
        self.extra_info = info or {}
        self._lock = threading.Lock()
        # LRU-bounded: the key includes client-controlled sampling
        # values (temperature must stay trace-static — the greedy
        # branch is Python-level control flow), so unbounded caching
        # would let varied traffic grow compiled programs without
        # limit.
        from collections import OrderedDict

        self._fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._fn_cap = 32
        self.requests = 0
        # Coalescing state: pending greedy requests by compile shape
        # (minus batch).  _pending_lock guards the queues only; the
        # device lock guards execution.
        self._pending: Dict[Tuple, list] = {}
        self._pending_lock = threading.Lock()
        self.coalesced_batches = 0
        self.coalesced_requests = 0
        # /metrics counters.  _stats_lock guards errors/latency/token
        # tallies (mutated from handler threads); requests/coalesced_*
        # are mutated under the DEVICE lock and read unlocked by
        # metrics_text — consistent enough for monotonic counters.
        self._stats_lock = threading.Lock()
        self.errors = 0
        self._lat_sum = 0.0
        self._lat_count = 0
        self._tokens_out = 0
        # PREFIX CACHE: post-prefill KV caches keyed by the exact
        # prompt batch, LRU-bounded (entries cost O(max_position)
        # device memory each — the system-prompt serving win).  A
        # request whose prompt extends a stored entry pays prefill
        # only for the suffix (models/generate.prefill's extension
        # contract); greedy/sampled solo requests only — beam/spec
        # tile or roll back the cache.  prefix_cache=0 disables.
        self.prefix_cache_size = int(prefix_cache)
        if not hasattr(model, "encode"):
            self._prefix_enabled = self.prefix_cache_size > 0
        else:
            self._prefix_enabled = False  # seq2seq: encoder != prefix
        self._prefix: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._prefix_lock = threading.Lock()
        self.prefix_hits = 0

    # -- compile cache --------------------------------------------------

    def _fn(self, key):
        import jax

        from .models import generate as G

        if key in self._fns:
            self._fns.move_to_end(key)
            return self._fns[key]
        kind, b, p_len, new, temp, top_k, top_p, eos, beams, chunk = key
        if kind == "beam":
            fn = jax.jit(lambda toks, rng: G.generate_beam(
                self.model, self.variables, toks, max_new_tokens=new,
                num_beams=beams, eos_id=eos, prefill_chunk=chunk))
        elif kind == "spec":
            k = beams  # slot reused for the draft length
            fn = jax.jit(lambda toks, rng: G.generate_speculative(
                self.model, self.variables, self.draft_model,
                self.draft_variables, toks, max_new_tokens=new,
                k=k, eos_id=eos, prefill_chunk=chunk,
                temperature=temp, top_k=top_k, top_p=top_p,
                rng=rng if temp != 0.0 else None))
        else:
            fn = jax.jit(lambda toks, rng: G.generate(
                self.model, self.variables, toks, max_new_tokens=new,
                temperature=temp, top_k=top_k, top_p=top_p,
                eos_id=eos, rng=rng, prefill_chunk=chunk))
        self._fns[key] = fn
        if len(self._fns) > self._fn_cap:
            self._fns.popitem(last=False)  # evict least-recently-used
        return fn

    # -- prefix cache ----------------------------------------------------

    def _split_fns(self, b: int, p_or_s: int, kind: str, chunk,
                   new=None, temp=None, top_k=None, top_p=None,
                   eos=None):
        """Jitted split programs for the prefix-cache path:
        ``pfill``/``extend`` produce (logits, cache); ``cont`` decodes
        from a cache.  Cached in the same LRU as the fused programs."""
        import jax

        from .models import generate as G

        # "cont" does not depend on chunk — keying it would compile
        # duplicate identical decode programs per chunk value.
        key = (kind, b, p_or_s, new, temp, top_k, top_p, eos, None,
               chunk if kind != "cont" else None)
        if key in self._fns:
            self._fns.move_to_end(key)
            return self._fns[key]
        if kind == "pfill":
            fn = jax.jit(lambda toks: G.prefill(
                self.model, self.variables, toks, chunk=chunk))
        elif kind == "extend":
            fn = jax.jit(lambda cache, toks, pos: G.prefill(
                self.model, self.variables, toks, chunk=chunk,
                cache=cache, position=pos))
        else:  # cont
            fn = jax.jit(lambda cache, logits, pos, rng:
                         G.generate_continue(
                             self.model, self.variables, cache,
                             logits, pos, max_new_tokens=new,
                             temperature=temp, top_k=top_k,
                             top_p=top_p, rng=rng, eos_id=eos,
                             _validated=True))
        self._fns[key] = fn
        if len(self._fns) > self._fn_cap:
            self._fns.popitem(last=False)
        return fn

    def _prefix_lookup(self, toks: np.ndarray):
        """Longest stored entry whose prompt is a prefix of ``toks``
        (same batch): returns (key, p_cached, logits, cache) or None."""
        b, p_len = toks.shape
        with self._prefix_lock:
            best = None
            for key, (rows, logits, cache) in self._prefix.items():
                pc = rows.shape[1]
                if rows.shape[0] != b or pc > p_len:
                    continue
                if (best is None or pc > best[1]) and \
                        np.array_equal(rows, toks[:, :pc]):
                    best = (key, pc, logits, cache)
            if best is not None:
                self._prefix.move_to_end(best[0])
        return best

    def _prefix_store(self, toks: np.ndarray, logits, cache) -> None:
        key = (toks.shape[0], toks.shape[1], toks.tobytes())
        with self._prefix_lock:
            if key in self._prefix:
                self._prefix.move_to_end(key)
                return
            self._prefix[key] = (toks.copy(), logits, cache)
            while len(self._prefix) > self.prefix_cache_size:
                self._prefix.popitem(last=False)

    def prefill_prompt(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /prefill: register a prompt (prefix) in the prefix
        cache — the system-prompt workflow.  Later /generate requests
        whose prompt starts with it skip its prefill entirely."""
        if not self._prefix_enabled:
            raise ValueError(
                "prefix cache is disabled on this server "
                "(start with --prefix-cache N)")
        import jax

        rows = _parse_prompt_rows(req, self.max_batch)
        cfg = getattr(self.model, "cfg", None)
        max_pos = getattr(cfg, "max_position", None)
        if max_pos is not None and len(rows[0]) > max_pos \
                and not getattr(cfg, "kv_cache_ring", False):
            # same contract as /generate: doomed requests fail in the
            # cheap validation layer, not at jit-trace time inside
            # the device lock (an over-capacity prefill would clamp
            # the cache write index into garbage).
            raise ValueError(
                f"prompt ({len(rows[0])}) exceeds the model's "
                f"max_position ({max_pos})")
        chunk = req.get("prefill_chunk")
        try:
            chunk = None if chunk is None else _int_param(chunk)
        except (TypeError, ValueError):
            # normalized 400, same contract as /generate (a list or
            # string here must not surface as a 500 TypeError)
            raise ValueError("prefill_chunk must be an int")
        if chunk is not None and chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        toks = np.asarray(rows, np.int32)
        t0 = time.perf_counter()
        with self._lock:
            logits, cache = self._split_fns(
                toks.shape[0], toks.shape[1], "pfill", chunk)(toks)
            jax.block_until_ready(logits)
            self._prefix_store(toks, logits, cache)
            self.requests += 1
        with self._stats_lock:
            self._lat_sum += time.perf_counter() - t0
            self._lat_count += 1
        return {"cached_rows": toks.shape[0],
                "cached_len": toks.shape[1],
                "entries": len(self._prefix)}

    def _generate_prefix_cached(self, toks: np.ndarray, p_len: int,
                                new: int, temp, top_k, top_p, eos,
                                chunk, seed, hit):
        """Solo decode through the split prefill/continue programs on
        a prefix-cache HIT, paying prefill only for the suffix (which
        is stored back, so sessions grow).  Exact: the split is the
        same program as fused generate (generate_continue's contract),
        and extension equals one-shot prefill (chunked-prefill
        contract)."""
        import jax
        import jax.random as jrandom

        b = toks.shape[0]
        with self._lock:
            _, pc, logits, cache = hit
            if pc < p_len:  # extend with the suffix, store back
                suffix = toks[:, pc:]
                logits, cache = self._split_fns(
                    b, suffix.shape[1], "extend", chunk)(
                        cache, suffix, pc)
                jax.block_until_ready(logits)
                self._prefix_store(toks, logits, cache)
            out_new = np.asarray(jax.device_get(self._split_fns(
                b, None, "cont", chunk, new=new, temp=temp,
                top_k=top_k, top_p=top_p, eos=eos)(
                    cache, logits, p_len, jrandom.PRNGKey(seed))))
            self.requests += 1
            self.prefix_hits += 1
        return np.concatenate([toks, out_new], axis=1)

    # -- coalesced execution --------------------------------------------

    def _drain(self, ckey) -> list:
        """Pop the longest prefix of ``ckey``'s queue that fits in
        max_batch (first item always fits: per-request batch is
        validated <= max_batch)."""
        with self._pending_lock:
            q = self._pending.get(ckey)
            if not q:
                return []
            batch, n = [], 0
            while q and n + q[0].toks.shape[0] <= self.max_batch:
                it = q.pop(0)
                batch.append(it)
                n += it.toks.shape[0]
            if not q:
                self._pending.pop(ckey, None)
            return batch

    def _execute_batch(self, ckey, batch) -> None:
        """Run one merged greedy batch; deliver each request's slice.

        Requests may differ in max_new_tokens (ckey excludes it): the
        batch decodes to the LONGEST request's length and each item is
        sliced back to its own — exact, because greedy rows never
        interact and eos-frozen rows just keep emitting eos past their
        requested budget (truncated away by the slice).

        Failures are delivered through item.error, never raised: the
        executing leader may not own any row of this batch, and its
        own request must not die for a stranger's OOM.
        """
        import jax
        import jax.random as jrandom

        p_len, eos, chunk = ckey
        try:
            rows = np.concatenate([it.toks for it in batch], axis=0)
            new = max(it.new for it in batch)
            n = rows.shape[0]
            b = _batch_bucket(n, self.max_batch)
            if b > n:  # batch-dim pad: rows never interact across it
                rows = np.concatenate(
                    [rows, np.repeat(rows[-1:], b - n, axis=0)], axis=0)
            # Same key format as the solo path, so coalesced buckets
            # and equal-sized solo requests share compiled programs.
            key = ("sample", b, p_len, new, 0.0, None, None, eos, 1,
                   chunk)
            fn = self._fn(key)
            out = np.asarray(jax.device_get(
                fn(rows, jrandom.PRNGKey(0))))
            ofs = 0
            for it in batch:
                r = it.toks.shape[0]
                it.result = out[ofs:ofs + r, :p_len + it.new]
                ofs += r
                it.event.set()
            self.requests += len(batch)
            if len(batch) > 1:
                self.coalesced_batches += 1
                self.coalesced_requests += len(batch)
        except BaseException as e:
            for it in batch:
                if not it.event.is_set():
                    it.error = e
                    it.event.set()

    def _generate_coalesced(self, toks: np.ndarray, p_len: int,
                            new: int, eos, chunk) -> np.ndarray:
        """Queue a greedy request; lead merged batches until ours is
        done.  Leader election is just lock acquisition: whoever gets
        the device lock drains and executes; everyone else's request
        was either in those batches (event set before the lock is
        released) or still queued for the next leader — so inside the
        lock, an unset event implies our item is drainable and every
        drain makes progress.
        """
        ckey = (p_len, eos, chunk)  # new excluded: lengths merge
        item = _Pending(toks, new)
        with self._pending_lock:
            self._pending.setdefault(ckey, []).append(item)
        with self._lock:
            while not item.event.is_set():
                batch = self._drain(ckey)
                if not batch:
                    # Invariant broken (e.g. max_batch shrunk below a
                    # queued request's rows after validation): fail
                    # loudly instead of waiting forever — and pull the
                    # orphaned item so no later leader runs it after
                    # this request has already errored out.
                    with self._pending_lock:
                        q = self._pending.get(ckey)
                        if q and item in q:
                            q.remove(item)
                            if not q:
                                self._pending.pop(ckey, None)
                    if not item.event.is_set():
                        raise RuntimeError(
                            "coalescing invariant broken: queued "
                            "request no longer drainable (max_batch "
                            "changed mid-flight?)")
                    break
                self._execute_batch(ckey, batch)
        item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result

    # -- request handling -----------------------------------------------

    def generate(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        rows = _parse_prompt_rows(req, self.max_batch)
        lens = [len(r) for r in rows]
        _int = _int_param

        def _float(v):
            # float(True) == 1.0: {"temperature": true} must not
            # silently switch greedy to temp-1.0 sampling.
            if isinstance(v, bool):
                raise ValueError("expected a number, got a boolean")
            return float(v)

        try:
            new = _int(req.get("max_new_tokens", 32))
            temp = _float(req.get("temperature", 0.0))
            top_k = req.get("top_k")
            top_k = None if top_k is None else _int(top_k)
            top_p = req.get("top_p")
            top_p = None if top_p is None else _float(top_p)
            eos = req.get("eos_id")
            eos = None if eos is None else _int(eos)
            beams = _int(req.get("num_beams", 1))
            seed = _int(req.get("seed", 0))
        except (TypeError, ValueError):
            raise ValueError(
                "sampling params must be scalars (temperature/top_p "
                "float, max_new_tokens/top_k/eos_id/num_beams/seed "
                "int, not booleans)")
        if new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if beams > 1 and (temp != 0.0 or top_k is not None
                          or top_p is not None):
            # Mirror the CLI: beam search is deterministic — dropping
            # sampling params silently would let a client believe it
            # sampled.
            raise ValueError(
                "beam search is deterministic; temperature/top_k/"
                "top_p cannot be combined with num_beams > 1")
        speculative = req.get("speculative", False)
        if not isinstance(speculative, bool):
            # bool("false") is True — a stringified flag must not
            # silently flip the decode mode.
            raise ValueError("'speculative' must be a JSON boolean")
        if speculative:
            if self.draft_model is None:
                raise ValueError(
                    "server has no draft model (start with "
                    "--draft-model to enable speculative decoding)")
            if beams > 1:
                raise ValueError(
                    "speculative decoding cannot combine with beam "
                    "search (greedy or sampled only)")
            if temp == 0.0 and (top_k is not None
                                or top_p is not None):
                # dropping the flags silently would let a client
                # believe it sampled (same contract as num_beams)
                raise ValueError(
                    "speculative top_k/top_p need temperature > 0 "
                    "(temperature=0 is greedy and would ignore them)")
            try:
                spec_k = _int(req.get("spec_k", 4))
            except (TypeError, ValueError):
                raise ValueError("spec_k must be an int")
            if spec_k < 1:
                raise ValueError("spec_k must be >= 1")
        chunk = req.get("prefill_chunk")
        try:
            chunk = None if chunk is None else _int(chunk)
        except (TypeError, ValueError):
            raise ValueError("prefill_chunk must be an int")
        if chunk is not None and chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        p_len0 = lens[0]
        if chunk is not None and chunk >= p_len0:
            # a chunk covering the whole prompt IS the single-forward
            # program — normalize so identical programs share one
            # compile-cache slot
            chunk = None

        p_len = lens[0]
        # Capacity checks for EVERY model a request will touch, so
        # doomed requests fail in this cheap validation layer instead
        # of inside the locked device section at jit-trace time.
        # Speculative rounds touch k-1 positions past the last
        # committed token (generate_speculative's guards).
        slack = (spec_k - 1) if speculative else 0
        models = [("model", self.model)]
        if speculative:
            models.append(("draft model", self.draft_model))
        for label, m in models:
            cfg = getattr(m, "cfg", None)
            max_pos = getattr(cfg, "max_position", None)
            if getattr(cfg, "kv_cache_ring", False):
                ring_slack = getattr(cfg, "kv_cache_ring_slack", 0)
                if speculative and ring_slack < spec_k - 1:
                    raise ValueError(
                        f"{label} needs kv_cache_ring_slack >= "
                        f"{spec_k - 1} for spec_k={spec_k} "
                        f"(got {ring_slack})")
                continue  # ring caches are position-keyed, unbounded
            if max_pos is not None and p_len + new + slack > max_pos:
                raise ValueError(
                    f"prompt ({p_len}) + max_new_tokens ({new})"
                    + (f" + spec_k-1 ({slack})" if slack else "")
                    + f" exceeds the {label}'s max_position "
                    f"({max_pos})")
        toks = np.asarray(rows, np.int32)

        t0 = time.perf_counter()
        # Prefix-cache hit (registered via /prefill): greedy/sampled
        # solo requests decode from the stored prefill — beam tiles
        # and speculative rolls back the cache, so they stay cold.
        prefix_hit = None
        if self._prefix_enabled and beams == 1 and not speculative:
            prefix_hit = self._prefix_lookup(toks)
        coalescible = (self.coalesce and not speculative
                       and beams == 1 and temp == 0.0
                       and top_k is None and top_p is None)
        if prefix_hit is not None:
            out = self._generate_prefix_cached(
                toks, p_len, new, temp, top_k, top_p, eos, chunk,
                seed, prefix_hit)
        elif coalescible:
            # Exactness argument for ignoring ``seed`` here: greedy
            # decoding never consults the PRNG, so requests with
            # different seeds still produce identical outputs merged
            # or solo.
            out = self._generate_coalesced(toks, p_len, new, eos,
                                           chunk)
        else:
            if speculative:
                # last slot carries the draft length (see _fn)
                key = ("spec", len(rows), p_len, new, temp, top_k,
                       top_p, eos, spec_k, chunk)
            else:
                key = ("beam", len(rows), p_len,
                       new, temp, top_k, top_p, eos, beams, chunk) \
                    if beams > 1 else \
                    ("sample", len(rows), p_len, new, temp, top_k,
                     top_p, eos, beams, chunk)
            with self._lock:  # one chip: serialize device work
                import jax.random as jrandom

                fn = self._fn(key)
                out = np.asarray(jax.device_get(
                    fn(toks, jrandom.PRNGKey(seed))))
                self.requests += 1
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self._lat_sum += dt
            self._lat_count += 1
            self._tokens_out += len(rows) * new
        return {
            "model": self.model_name,
            "new_tokens": out[:, p_len:].tolist(),
            "tokens": out.tolist(),
            "wall_s": round(dt, 4),
            "tok_per_sec": round(len(rows) * new / dt, 1),
            **({"prefix_hit_len": prefix_hit[1]}
               if prefix_hit is not None else {}),
        }

    def info(self) -> Dict[str, Any]:
        import jax

        cfg = getattr(self.model, "cfg", None)
        summary = {}
        if cfg is not None:
            for f in ("vocab_size", "hidden_size", "d_model",
                      "num_layers", "num_heads", "max_position",
                      "kv_cache_int8"):
                v = getattr(cfg, f, None)
                if v is not None:
                    summary[f] = v
        return {"model": self.model_name, "config": summary,
                "backend": jax.default_backend(),
                "max_batch": self.max_batch,
                "compiled_shapes": len(self._fns),
                "requests": self.requests,
                "coalesced_batches": self.coalesced_batches,
                "coalesced_requests": self.coalesced_requests,
                "prefix_entries": len(self._prefix),
                "prefix_hits": self.prefix_hits,
                **self.extra_info}

    def metrics_text(self) -> str:
        """Prometheus text exposition of the serving counters —
        the observability surface a scraping stack expects from an
        in-cluster `V1Service` (SURVEY §5.5)."""
        with self._stats_lock:
            lat_sum, lat_count = self._lat_sum, self._lat_count
            toks, errs = self._tokens_out, self.errors
        lines = [
            "# TYPE ptpu_serving_requests_total counter",
            f"ptpu_serving_requests_total {self.requests}",
            "# TYPE ptpu_serving_errors_total counter",
            f"ptpu_serving_errors_total {errs}",
            "# TYPE ptpu_serving_tokens_generated_total counter",
            f"ptpu_serving_tokens_generated_total {toks}",
            "# TYPE ptpu_serving_coalesced_batches_total counter",
            f"ptpu_serving_coalesced_batches_total "
            f"{self.coalesced_batches}",
            "# TYPE ptpu_serving_coalesced_requests_total counter",
            f"ptpu_serving_coalesced_requests_total "
            f"{self.coalesced_requests}",
            "# TYPE ptpu_serving_request_seconds summary",
            f"ptpu_serving_request_seconds_sum {lat_sum:.6f}",
            f"ptpu_serving_request_seconds_count {lat_count}",
            "# TYPE ptpu_serving_compiled_programs gauge",
            f"ptpu_serving_compiled_programs {len(self._fns)}",
            "# TYPE ptpu_serving_prefix_hits_total counter",
            f"ptpu_serving_prefix_hits_total {self.prefix_hits}",
            "# TYPE ptpu_serving_prefix_entries gauge",
            f"ptpu_serving_prefix_entries {len(self._prefix)}",
        ]
        return "\n".join(lines) + "\n"


def make_server(host: str, port: int, ms: ModelServer
                ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def _send_raw(self, code: int, body: bytes,
                      ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send(self, code: int, obj: Dict[str, Any]) -> None:
            self._send_raw(code, json.dumps(obj).encode(),
                           "application/json")

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok",
                                 "model": ms.model_name})
            elif self.path == "/info":
                self._send(200, ms.info())
            elif self.path == "/metrics":
                self._send_raw(200, ms.metrics_text().encode(),
                               "text/plain; version=0.0.4")
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path not in ("/generate", "/prefill"):
                self._send(404, {"error": f"no route {self.path}"})
                return
            handler = ms.generate if self.path == "/generate" \
                else ms.prefill_prompt
            # Generate FIRST, send after: a client hanging up while a
            # successful response streams out must not count as a
            # serving error (nor trigger a doomed second send).
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                code, resp = 200, handler(req)
            except ValueError as e:
                with ms._stats_lock:
                    ms.errors += 1
                code, resp = 400, {"error": str(e)}
            except Exception as e:  # never kill the server thread
                with ms._stats_lock:
                    ms.errors += 1
                code, resp = 500, {"error": f"{type(e).__name__}: {e}"}
            try:
                self._send(code, resp)
            except OSError:
                pass  # client went away mid-write; nothing to do

    return ThreadingHTTPServer((host, port), Handler)
