"""Native model server: the zoo's decode stack behind HTTP.

The reference's serving story is `V1Service` — it schedules an opaque
user container and port-forwards to it (SURVEY.md §2.4); the model
server inside is the user's problem.  Here the framework owns the
decode loop, so it ships the server too: one process, stdlib HTTP
(same no-dependency stance as the control plane), jit-compiled
generate with a shape-bucketed compile cache.

Endpoints:

- ``GET  /healthz``  -> ``{"status": "ok", ...}`` (readiness; also the
  operator's gang-health convention)
- ``GET  /info``     -> model name, config summary, quantization flags
- ``POST /generate`` -> ``{"prompt": [ids] | [[ids], ...],
  "max_new_tokens": N, "temperature": t, "top_k": k, "top_p": p,
  "eos_id": e, "num_beams": B, "speculative": bool, "spec_k": K,
  "seed": s, "prefill_chunk": C}`` -> tokens + timing (speculative needs a server-side
  draft model and is greedy-only)

Shape discipline: each distinct (batch, prompt_len, max_new_tokens,
decode-mode) compiles once and is cached.  Prompts are NOT padded:
the zoo's decode path has no attention-mask input, so left-padding
would let real tokens attend to pad positions (silently wrong
output).  Clients with ragged traffic should bucket prompt lengths
themselves; every row in one request must share a length.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np


class ModelServer:
    """Wraps one model + params; owns the compile cache and the lock
    serializing device work (one chip — concurrent requests queue)."""

    def __init__(self, model, variables, *, model_name: str = "model",
                 max_batch: int = 8,
                 draft_model=None, draft_variables=None,
                 info: Optional[Dict[str, Any]] = None):
        self.model = model
        self.variables = variables
        # Optional speculative-decoding draft: requests opt in with
        # {"speculative": true}; greedy-only, output identical to the
        # plain greedy decode (models/generate.generate_speculative).
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        self.model_name = model_name
        self.max_batch = int(max_batch)
        self.extra_info = info or {}
        self._lock = threading.Lock()
        # LRU-bounded: the key includes client-controlled sampling
        # values (temperature must stay trace-static — the greedy
        # branch is Python-level control flow), so unbounded caching
        # would let varied traffic grow compiled programs without
        # limit.
        from collections import OrderedDict

        self._fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._fn_cap = 32
        self.requests = 0

    # -- compile cache --------------------------------------------------

    def _fn(self, key):
        import jax

        from .models import generate as G

        if key in self._fns:
            self._fns.move_to_end(key)
            return self._fns[key]
        kind, b, p_len, new, temp, top_k, top_p, eos, beams, chunk = key
        if kind == "beam":
            fn = jax.jit(lambda toks, rng: G.generate_beam(
                self.model, self.variables, toks, max_new_tokens=new,
                num_beams=beams, eos_id=eos, prefill_chunk=chunk))
        elif kind == "spec":
            k = beams  # slot reused for the draft length
            fn = jax.jit(lambda toks, rng: G.generate_speculative(
                self.model, self.variables, self.draft_model,
                self.draft_variables, toks, max_new_tokens=new,
                k=k, eos_id=eos, prefill_chunk=chunk))
        else:
            fn = jax.jit(lambda toks, rng: G.generate(
                self.model, self.variables, toks, max_new_tokens=new,
                temperature=temp, top_k=top_k, top_p=top_p,
                eos_id=eos, rng=rng, prefill_chunk=chunk))
        self._fns[key] = fn
        if len(self._fns) > self._fn_cap:
            self._fns.popitem(last=False)  # evict least-recently-used
        return fn

    # -- request handling -----------------------------------------------

    def generate(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        if not isinstance(req, dict):
            raise ValueError("request body must be a JSON object")
        rows = req.get("prompt")
        if rows is None:
            raise ValueError("missing 'prompt'")
        if not isinstance(rows, list):
            raise ValueError("'prompt' must be a list of token ids "
                             "or a list of rows")
        if rows and not isinstance(rows[0], list):
            rows = [rows]
        if not rows or not rows[0]:
            raise ValueError("prompt must contain at least one token")
        if len(rows) > self.max_batch:
            raise ValueError(f"batch {len(rows)} exceeds max_batch "
                             f"{self.max_batch}")
        lens = [len(r) for r in rows]
        if len(set(lens)) != 1:
            # No silent padding: the decode path has no attention
            # mask, so padded positions would be attended to.
            raise ValueError(
                "all prompt rows must share one length (the decode "
                "path has no pad mask; bucket lengths client-side)")
        if any(not all(isinstance(t, int) for t in r) for r in rows):
            raise ValueError("prompt rows must be integer token ids")
        new = int(req.get("max_new_tokens", 32))
        if new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        try:
            temp = float(req.get("temperature", 0.0))
            top_k = req.get("top_k")
            top_k = None if top_k is None else int(top_k)
            top_p = req.get("top_p")
            top_p = None if top_p is None else float(top_p)
            eos = req.get("eos_id")
            eos = None if eos is None else int(eos)
            beams = int(req.get("num_beams", 1))
            seed = int(req.get("seed", 0))
        except (TypeError, ValueError):
            raise ValueError(
                "sampling params must be scalars (temperature/top_p "
                "float, top_k/eos_id/num_beams/seed int)")
        if beams > 1 and (temp != 0.0 or top_k is not None
                          or top_p is not None):
            # Mirror the CLI: beam search is deterministic — dropping
            # sampling params silently would let a client believe it
            # sampled.
            raise ValueError(
                "beam search is deterministic; temperature/top_k/"
                "top_p cannot be combined with num_beams > 1")
        speculative = req.get("speculative", False)
        if not isinstance(speculative, bool):
            # bool("false") is True — a stringified flag must not
            # silently flip the decode mode.
            raise ValueError("'speculative' must be a JSON boolean")
        if speculative:
            if self.draft_model is None:
                raise ValueError(
                    "server has no draft model (start with "
                    "--draft-model to enable speculative decoding)")
            if beams > 1 or temp != 0.0 or top_k is not None \
                    or top_p is not None:
                raise ValueError(
                    "speculative decoding is greedy-only (no "
                    "num_beams/temperature/top_k/top_p)")
            try:
                spec_k = int(req.get("spec_k", 4))
            except (TypeError, ValueError):
                raise ValueError("spec_k must be an int")
            if spec_k < 1:
                raise ValueError("spec_k must be >= 1")
        chunk = req.get("prefill_chunk")
        try:
            chunk = None if chunk is None else int(chunk)
        except (TypeError, ValueError):
            raise ValueError("prefill_chunk must be an int")
        if chunk is not None and chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        p_len0 = lens[0]
        if chunk is not None and chunk >= p_len0:
            # a chunk covering the whole prompt IS the single-forward
            # program — normalize so identical programs share one
            # compile-cache slot
            chunk = None

        p_len = lens[0]
        # Capacity checks for EVERY model a request will touch, so
        # doomed requests fail in this cheap validation layer instead
        # of inside the locked device section at jit-trace time.
        # Speculative rounds touch k-1 positions past the last
        # committed token (generate_speculative's guards).
        slack = (spec_k - 1) if speculative else 0
        models = [("model", self.model)]
        if speculative:
            models.append(("draft model", self.draft_model))
        for label, m in models:
            cfg = getattr(m, "cfg", None)
            max_pos = getattr(cfg, "max_position", None)
            if getattr(cfg, "kv_cache_ring", False):
                ring_slack = getattr(cfg, "kv_cache_ring_slack", 0)
                if speculative and ring_slack < spec_k - 1:
                    raise ValueError(
                        f"{label} needs kv_cache_ring_slack >= "
                        f"{spec_k - 1} for spec_k={spec_k} "
                        f"(got {ring_slack})")
                continue  # ring caches are position-keyed, unbounded
            if max_pos is not None and p_len + new + slack > max_pos:
                raise ValueError(
                    f"prompt ({p_len}) + max_new_tokens ({new})"
                    + (f" + spec_k-1 ({slack})" if slack else "")
                    + f" exceeds the {label}'s max_position "
                    f"({max_pos})")
        toks = np.asarray(rows, np.int32)

        if speculative:
            # last slot carries the draft length (see _fn)
            key = ("spec", len(rows), p_len, new, 0.0, None, None,
                   eos, spec_k, chunk)
        else:
            key = ("beam" if beams > 1 else "sample", len(rows), p_len,
                   new, temp, top_k, top_p, eos, beams, chunk)
        t0 = time.perf_counter()
        with self._lock:  # one chip: serialize device work
            import jax.random as jrandom

            fn = self._fn(key)
            out = np.asarray(jax.device_get(
                fn(toks, jrandom.PRNGKey(seed))))
            self.requests += 1
        dt = time.perf_counter() - t0
        return {
            "model": self.model_name,
            "new_tokens": out[:, p_len:].tolist(),
            "tokens": out.tolist(),
            "wall_s": round(dt, 4),
            "tok_per_sec": round(len(rows) * new / dt, 1),
        }

    def info(self) -> Dict[str, Any]:
        import jax

        cfg = getattr(self.model, "cfg", None)
        summary = {}
        if cfg is not None:
            for f in ("vocab_size", "hidden_size", "d_model",
                      "num_layers", "num_heads", "max_position",
                      "kv_cache_int8"):
                v = getattr(cfg, f, None)
                if v is not None:
                    summary[f] = v
        return {"model": self.model_name, "config": summary,
                "backend": jax.default_backend(),
                "max_batch": self.max_batch,
                "compiled_shapes": len(self._fns),
                "requests": self.requests, **self.extra_info}


def make_server(host: str, port: int, ms: ModelServer
                ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, obj: Dict[str, Any]) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok",
                                 "model": ms.model_name})
            elif self.path == "/info":
                self._send(200, ms.info())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/generate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                self._send(200, ms.generate(req))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # never kill the server thread
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    return ThreadingHTTPServer((host, port), Handler)
