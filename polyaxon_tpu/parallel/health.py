"""Slice health checks (SURVEY.md 5.3: failure detection).

The operator supervises pods and the control plane sweeps zombie
heartbeats; this module covers the third failure mode — the process is
alive but the ACCELERATOR fabric under it is not (wedged TPU runtime,
a chip dropped off the ICI torus after preemption, a tunnel that hangs
instead of raising).  ``check_slice_health`` runs a tiny all-device
collective with a deadline in a worker thread: a healthy slice answers
in milliseconds; a wedged one hangs, the deadline fires, and the caller
can checkpoint-and-exit so the operator reschedules the gang
(TPU slices cannot resize elastically — restart is the recovery).

``train.py`` runs it right after distributed bootstrap, before touching
the checkpoint.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)


@dataclass
class SliceHealth:
    ok: bool
    detail: str
    latency_s: Optional[float] = None
    n_devices: int = 0


def check_slice_health(mesh=None, timeout_s: float = 60.0) -> SliceHealth:
    """Prove every device in the mesh (default: all devices) can compute
    and communicate: an all-device psum of ones must return n_devices.

    Never raises; never hangs past ``timeout_s`` (the probe runs in a
    daemon thread — a wedged runtime strands that thread, not the
    caller, mirroring bench.py's never-kill-mid-init lesson).
    """
    import jax

    devices = list(mesh.devices.flat) if mesh is not None \
        else jax.devices()
    n = len(devices)
    result: dict = {}

    def probe():
        try:
            import numpy as np

            import jax.numpy as jnp
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)

            probe_mesh = Mesh(np.asarray(devices), ("all",))
            ones = jnp.ones((n,), jnp.float32)
            arr = jax.device_put(
                ones, NamedSharding(probe_mesh, P("all")))
            total = jax.jit(
                jnp.sum,
                out_shardings=NamedSharding(probe_mesh, P()))(arr)
            result["value"] = float(jax.device_get(total))
        except Exception as e:  # noqa: BLE001 - report, don't raise
            result["error"] = f"{type(e).__name__}: {e}"

    start = time.monotonic()
    thread = threading.Thread(target=probe, daemon=True,
                              name="ptpu-slice-health")
    thread.start()
    thread.join(timeout=timeout_s)
    latency = time.monotonic() - start

    if thread.is_alive():
        return SliceHealth(
            ok=False, latency_s=None, n_devices=n,
            detail=f"collective probe hung past {timeout_s:.0f}s "
                   f"(runtime wedged?); probe thread left to finish")
    if "error" in result:
        return SliceHealth(ok=False, latency_s=latency, n_devices=n,
                           detail=result["error"])
    value = result.get("value")
    if value != float(n):
        return SliceHealth(
            ok=False, latency_s=latency, n_devices=n,
            detail=f"psum over {n} devices returned {value}")
    return SliceHealth(ok=True, latency_s=latency, n_devices=n,
                       detail=f"{n} devices healthy")
