"""Pipeline parallelism: GPipe-style schedule over the ``pp`` mesh axis.

The reference has no tensor-level pipeline support (SURVEY.md 2.12); here
stages live on mesh devices and activations move stage-to-stage with
``ppermute`` (one ICI hop on TPU).  The schedule is a single ``lax.scan``
over ``n_micro + n_stages - 1`` ticks: in steady state every stage
computes one microbatch per tick while the permute of the previous tick's
activations rides the ICI in parallel — XLA overlaps the two.

Assumes homogeneous stages (a stack of identical blocks — the transformer
case): each device holds its own stage's params; stage0 additionally owns
embedding, the last stage the head (handled by the caller's stage_fn via
the stage index).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import active_batch_axes


def _manual_axes(mesh):
    """Axes the pipeline shard_map runs MANUAL over.

    pp x tp composition (VERDICT r3 missing #4): when the mesh has a
    real tp axis, it is left AUTO so GSPMD shards the stage-internal
    matmuls over tp from the stacked params' jit-level shardings —
    partial-manual shard_map, no manual collectives in the blocks.
    All remaining (size-1) axes stay manual: semantically identical,
    and it sidesteps an XLA:CPU crash ("Invalid binary instruction
    opcode copy") when a whole-program jit contains a partial-manual
    region — the TPU compiler handles partial-manual fine (verified
    via a deviceless v5e compile, tests/test_pp_tp.py), so the only
    configuration that cannot run under jit on the virtual CPU mesh
    is tp>1, which CI covers eagerly + compile-only instead.
    """
    auto = {a for a in ("tp",) if mesh.shape.get(a, 1) > 1}
    return frozenset(mesh.axis_names) - auto


def _vma_of(x):
    """x's varying-manual-axes set; empty on older jax, which has no
    VMA tracking (jax.typeof/pcast landed with the modern shard_map
    surface) — there the promotion below is unnecessary by the same
    token."""
    return jax.typeof(x).vma if hasattr(jax, "typeof") else ()


def _pvary_to(x, axes):
    """Promote x's varying-manual-axes set to include ``axes``.

    Partial-manual shard_map (pp x tp composition) runs with
    check_vma=True, which makes scan carries and cond branches strict
    about VMA agreement; inputs replicated over pp (spec doesn't
    mention it) must be explicitly promoted before they meet
    pp-varying values in a carry.  No-op on older jax (no VMA
    tracking to promote within).
    """
    if not hasattr(jax, "typeof"):
        return x
    have = jax.typeof(x).vma
    missing = tuple(a for a in axes if a not in have)
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def _pipeline_shard(params, x_micro, *, axis_name: str, stage_fn,
                    n_micro: int):
    """Per-shard body.

    params:  this stage's params (pytree, local).
    x_micro: [n_micro, mb, ...] input microbatches (only stage 0's are
             real; other stages receive garbage they ignore).
    Returns [n_micro, mb, ...] outputs (valid on the LAST stage).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    # x arrives replicated over pp (spec P(None, bspec)); promote so
    # scan carries / cond branches that mix it with pp-varying values
    # agree under check_vma=True.
    x_micro = _pvary_to(x_micro, (axis_name,))
    buf_shape = x_micro.shape[1:]
    out_accum = jnp.zeros_like(x_micro)

    def tick(carry, t):
        carried_act, out_accum = carry
        # Stage 0 ingests microbatch t (while t < n_micro); other stages
        # consume what arrived from the left neighbor.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, 0,
                                              keepdims=False)
        x_in = jnp.where(stage == 0, inject, carried_act)
        y = stage_fn(stage, params, x_in)
        # Last stage writes its result for microbatch (t - n_stages + 1).
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = jnp.logical_and(stage == n_stages - 1,
                                t >= n_stages - 1)
        out_accum = jax.lax.cond(
            write,
            lambda acc: jax.lax.dynamic_update_index_in_dim(
                acc, y, out_idx, 0),
            lambda acc: acc,
            out_accum,
        )
        # Move activations right one stage for the next tick.
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return (nxt, out_accum), None

    init = (_pvary_to(jnp.zeros(buf_shape, x_micro.dtype),
                      _vma_of(x_micro)), out_accum)
    (_, out_accum), _ = jax.lax.scan(tick, init, jnp.arange(total))
    return out_accum


def pipeline_apply(
    stage_fn: Callable[[jax.Array, Any, jax.Array], jax.Array],
    params_stacked: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "pp",
    n_micro: int = 4,
    batch_axes=("dp", "fsdp"),
) -> jax.Array:
    """Run a homogeneous pipeline.

    stage_fn(stage_index, stage_params, x) -> y  (same shape as x).
    params_stacked: pytree whose leaves have a leading [n_stages] axis
    (stage i's slice lives on pipeline rank i).
    x: GLOBAL [batch, ...]; batch must divide n_micro * microbatch.
    Returns y with x's sharding; results are only meaningful after the
    caller reads them from the last stage (psum-broadcast below makes the
    value uniform across the pp axis so downstream code is simple).
    """
    try:
        from jax import shard_map
    except ImportError:   # older jax: translated spellings
        from ._shard_map_compat import shard_map

    n_stages = mesh.shape.get(axis_name, 1)
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(f"Batch {batch} must divide into {n_micro} microbatches")
    mb = batch // n_micro

    bspec = active_batch_axes(mesh, batch_axes)
    param_spec = jax.tree.map(lambda _: P(axis_name), params_stacked)
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    def body(params, xm):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        out = _pipeline_shard(params, xm, axis_name=axis_name,
                              stage_fn=stage_fn, n_micro=n_micro)
        # Broadcast the last stage's result to all pp ranks.
        n = jax.lax.psum(1, axis_name)
        stage = jax.lax.axis_index(axis_name)
        out = jnp.where(stage == n - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis_name)

    out_micro = shard_map(
        body, mesh=mesh,
        in_specs=(param_spec, P(None, bspec)),
        out_specs=P(None, bspec),
        axis_names=_manual_axes(mesh),
        # Partial-manual REQUIRES vma checking: with check_vma=False
        # jax conservatively appends every mesh axis to out_specs,
        # which then collides with the auto axes.
        check_vma=True,
    )(params_stacked, x_micro)
    return out_micro.reshape((batch,) + out_micro.shape[2:])


def pipelined_lm_loss(model, block, mesh, *, n_micro: int = 0,
                      stack_keys=("h", "block")):
    """Train-step loss that routes a scanned transformer's block stack
    through the ``pp`` pipeline (VERDICT r1 #5: ``strategy: {pp: N}``
    must mean something end-to-end).

    ``model`` decomposes via ``embed_tokens``/``head`` methods (embedding
    and head run on every pipeline rank — tiny next to the stack);
    ``block`` is one layer module whose stacked params live under
    ``params["params"][stack_keys...]`` with a leading [num_layers] axis
    (the nn.scan layout).  Stages rematerialize per layer when the model
    config asks for remat.
    """
    import jax.numpy as jnp
    import optax

    cfg = model.cfg
    n_stages = mesh.shape.get("pp", 1)
    if cfg.num_layers % max(n_stages, 1):
        raise ValueError(
            f"pp={n_stages} must divide num_layers={cfg.num_layers}")
    per_stage = cfg.num_layers // max(n_stages, 1)
    micro = n_micro or 2 * n_stages

    def loss(params, batch, rng):
        tokens = batch["inputs"]
        x = model.apply(params, tokens, method="embed_tokens")

        stack = params["params"]
        for key in stack_keys:
            stack = stack[key]
        stacked = jax.tree.map(
            lambda p: p.reshape((n_stages, per_stage) + p.shape[1:]),
            stack)

        def one_layer(h, layer_params):
            return block.apply({"params": layer_params}, h), None

        body = jax.checkpoint(one_layer) if getattr(cfg, "remat", False) \
            else one_layer

        def stage_fn(stage_idx, stage_params, h):
            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        x = pipeline_apply(stage_fn, stacked, x.astype(cfg.dtype), mesh,
                           n_micro=micro)
        logits = model.apply(params, x, method="head")
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]).mean()
        return l, {"perplexity": jnp.exp(l)}

    return loss


def pipelined_lm_loss_1f1b(model, block, mesh, *, n_micro: int = 0,
                           stack_keys=("h", "block"),
                           axis_name: str = "pp"):
    """1F1B pipeline schedule for any scanned decoder in the zoo
    (GPT-2, Llama) — VERDICT r2 task 5.

    Why not GPipe-with-autodiff (``pipelined_lm_loss``): reversing the
    schedule scan stores one carried activation per TICK, i.e. O(n_micro)
    microbatch activations per stage, which caps n_micro, and the bubble
    fraction 2(S-1)/(2(n_micro+S-1)) shrinks only as n_micro grows.
    Here each scan tick runs ONE fwd slot and ONE bwd slot per stage
    (the 1F1B steady state) with a MANUAL per-stage VJP: the bwd slot
    re-runs its stage forward from a stashed stage INPUT (remat-style)
    and accumulates param grads inside the schedule.  Live activation
    memory per stage is the stash ring of min(2S-1, n_micro) microbatch
    inputs — O(S), independent of n_micro — so n_micro can grow until
    the bubble 2(S-1)/(n_micro + 2(S-1)) is negligible.

    Timeline (stage s, micro i, S stages): fwd at tick i + s; the last
    stage runs head+loss+d(head) for the micro it just forwarded in the
    same tick; bwd at tick i + 2(S-1) - s.  Activations ppermute right,
    cotangents ppermute left — both ride ICI in parallel with compute.
    Total ticks: n_micro + 2(S-1).

    Grads computed inside the schedule surface through a
    ``jax.custom_vjp`` whose forward IS the combined fwd+bwd program —
    outer ``jax.value_and_grad`` (TrainStep) works unchanged, and the
    embedding still differentiates through the returned x_micro
    cotangent (summing naturally with tied-head contributions).

    COST MODEL — the bubble is COMPUTE, not idle time (VERDICT r3 weak
    #5): every scan tick runs a full fwd slot and a full vjp-
    recompute+bwd slot on EVERY stage, masked off when inactive, so an
    inactive tick burns the same FLOPs as an active one.  Efficiency is
    therefore n_micro / (n_micro + 2(S-1)); GPipe's analogous fraction
    is (n_micro + S-1)^-1-shaped and LOWER at equal n_micro.  1F1B's
    win is exclusively memory: the O(S) stash ring lets n_micro grow
    (GPipe's activation memory is O(n_micro)), and at the n_micro GPipe
    cannot reach, 1F1B's overhead drops below GPipe's memory-feasible
    best.  Pick GPipe when activations fit; 1F1B when they don't.
    Numbers + the interleaved-1F1B waiver: PARITY.md "Pipeline bubble
    accounting".

    This is a TRAIN-ONLY loss: the primal path runs the combined
    fwd+bwd schedule even when no gradients are requested, so a
    forward-only/eval call pays the full backward.  Use the plain
    (non-pipelined) loss for eval.

    Like the GPipe path, pp composes with dp/fsdp batch sharding AND
    with tensor parallelism: the schedule's shard_map is manual over
    pp + batch axes only, leaving tp AUTO so GSPMD shards the
    stage-internal matmuls over tp from the params' jit-level
    shardings (``strategy: {pp: 2, tp: 2}``).
    """
    import numpy as np
    import optax
    try:
        from jax import shard_map
    except ImportError:   # older jax: translated spellings
        from ._shard_map_compat import shard_map

    cfg = model.cfg
    n_stages = mesh.shape.get(axis_name, 1)
    if cfg.num_layers % max(n_stages, 1):
        raise ValueError(
            f"pp={n_stages} must divide num_layers={cfg.num_layers}")
    micro = n_micro or 2 * n_stages
    stack_root = stack_keys[0]
    batch_axes = tuple(a for a in ("dp", "fsdp")
                       if mesh.shape.get(a, 1) > 1)
    n_batch_shards = int(np.prod([mesh.shape[a] for a in batch_axes],
                                 dtype=np.int64)) if batch_axes else 1
    use_remat = bool(getattr(cfg, "remat", False))

    def stage_fwd(stage_params, h):
        def one_layer(h, lp):
            return block.apply({"params": lp}, h), None
        body = jax.checkpoint(one_layer) if use_remat else one_layer
        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    def head_loss(nonstack, y, tgt):
        logits = model.apply({"params": nonstack}, y, method="head")
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tgt[:, 1:]).mean()

    def schedule(stack, nonstack, x_micro, tgt_micro):
        """shard_map body (per pp rank): the combined fwd+bwd 1F1B
        program.  Returns (loss_sum_local, dstack_local, dnonstack,
        dx_micro) — reductions over pp/batch axes applied below."""
        s = jax.lax.axis_index(axis_name)
        is_last = s == n_stages - 1
        m = x_micro.shape[0]
        depth = min(2 * n_stages - 1, m)  # stash ring: O(S) not O(m)
        right = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        left = [(j, (j - 1) % n_stages) for j in range(n_stages)]
        act_shape = x_micro.shape[1:]
        # check_vma=True (required for partial-manual pp x tp): promote
        # every input to the full manual VMA set up front so scan
        # carries and cond branches built from them agree — specs leave
        # stack replicated over batch axes, x/tgt over pp, nonstack
        # over everything.
        full_vma = tuple(sorted({axis_name, *(batch_axes or ())}))
        stack = jax.tree.map(lambda v: _pvary_to(v, full_vma), stack)
        nonstack = jax.tree.map(lambda v: _pvary_to(v, full_vma),
                                nonstack)
        x_micro = _pvary_to(x_micro, full_vma)
        tgt_micro = _pvary_to(tgt_micro, full_vma)
        # d(global mean loss)/d(loss_i) — seeds every vjp below so the
        # accumulated grads come out exactly scaled.  Promoted: vjp
        # cotangents must carry the primal output's VMA.
        seed = _pvary_to(jnp.float32(1.0 / (m * n_batch_shards)),
                         full_vma)

        def tick(carry, t):
            act_in, grad_in, stash, dstack, dnon, dx_mic, loss_acc = carry
            # ---- forward slot: micro i_f = t - s
            i_f = t - s
            active_f = (i_f >= 0) & (i_f < m)
            i_f_c = jnp.clip(i_f, 0, m - 1)
            inject = jax.lax.dynamic_index_in_dim(x_micro, i_f_c, 0,
                                                  keepdims=False)
            x_in = jnp.where(s == 0, inject, act_in)
            y = stage_fwd(stack, x_in)
            stash = jax.lax.cond(
                active_f,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, x_in, i_f_c % depth, 0),
                lambda b: b, stash)
            # Last stage only (lax.cond: the vocab-sized head must not
            # burn FLOPs on every stage every tick): loss + d(head) for
            # the micro just forwarded — its bwd slot is THIS tick.
            tgt = jax.lax.dynamic_index_in_dim(tgt_micro, i_f_c, 0,
                                               keepdims=False)

            def run_head(args):
                nonstack_, y_, tgt_ = args
                loss_i, head_vjp = jax.vjp(
                    lambda p, yy: head_loss(p, yy, tgt_), nonstack_, y_)
                dnon_i, dy = head_vjp(seed)
                return loss_i, dnon_i, dy

            def skip_head(args):
                nonstack_, y_, _ = args
                return (_pvary_to(jnp.zeros((), jnp.float32), full_vma),
                        jax.tree.map(jnp.zeros_like, nonstack_),
                        jnp.zeros_like(y_))

            loss_i, dnon_i, dy_head = jax.lax.cond(
                is_last & active_f, run_head, skip_head,
                (nonstack, y, tgt))
            loss_acc = loss_acc + loss_i
            dnon = jax.tree.map(jnp.add, dnon, dnon_i)
            # ---- backward slot: micro i_b = t - 2(S-1) + s
            i_b = t - 2 * (n_stages - 1) + s
            active_b = (i_b >= 0) & (i_b < m)
            i_b_c = jnp.clip(i_b, 0, m - 1)
            x_stash = jax.lax.dynamic_index_in_dim(stash, i_b_c % depth,
                                                   0, keepdims=False)
            dy = jnp.where(is_last, dy_head, grad_in)
            _, stage_vjp = jax.vjp(stage_fwd, stack, x_stash)
            dp_i, dx_i = stage_vjp(dy)
            dstack = jax.tree.map(
                lambda a, g: a + jnp.where(active_b, g,
                                           jnp.zeros_like(g)),
                dstack, dp_i)
            dx_i = jnp.where(active_b, dx_i, jnp.zeros_like(dx_i))
            dx_mic = jax.lax.cond(
                active_b & (s == 0),
                lambda d: jax.lax.dynamic_update_index_in_dim(
                    d, dx_i.astype(d.dtype), i_b_c, 0),
                lambda d: d, dx_mic)
            # ---- communicate: activations right, cotangents left.
            act_next = jax.lax.ppermute(y, axis_name, right)
            grad_next = jax.lax.ppermute(dx_i, axis_name, left)
            return (act_next, grad_next, stash, dstack, dnon, dx_mic,
                    loss_acc), None

        carry = (
            _pvary_to(jnp.zeros(act_shape, x_micro.dtype), full_vma),
            _pvary_to(jnp.zeros(act_shape, x_micro.dtype), full_vma),
            _pvary_to(jnp.zeros((depth,) + act_shape, x_micro.dtype),
                      full_vma),
            jax.tree.map(jnp.zeros_like, stack),
            jax.tree.map(jnp.zeros_like, nonstack),
            jnp.zeros_like(x_micro),
            _pvary_to(jnp.zeros((), jnp.float32), full_vma),
        )
        total = m + 2 * (n_stages - 1)
        (_, _, _, dstack, dnon, dx_mic, loss_acc), _ = jax.lax.scan(
            tick, carry, jnp.arange(total))

        # loss/dnon live on the last stage, dx on stage 0 (zeros
        # elsewhere) -> psum over pp; grads sum over batch shards; the
        # loss averages over them (each shard saw different data).
        loss = jax.lax.psum(loss_acc, axis_name) / m
        if batch_axes:
            loss = jax.lax.pmean(loss, batch_axes)
            dnon = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axes), dnon)
            dstack = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axes), dstack)
        dnon = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), dnon)
        dx_mic = jax.lax.psum(dx_mic, axis_name)
        return loss, dstack, dnon, dx_mic

    def run_schedule(stack, nonstack, x_micro, tgt_micro):
        bspec = active_batch_axes(mesh, ("dp", "fsdp"))
        stack_spec = jax.tree.map(lambda _: P(axis_name), stack)
        non_spec = jax.tree.map(lambda _: P(), nonstack)
        return shard_map(
            schedule, mesh=mesh,
            in_specs=(stack_spec, non_spec, P(None, bspec),
                      P(None, bspec)),
            out_specs=(P(), stack_spec, non_spec, P(None, bspec)),
            # tp stays auto when real — see _manual_axes.
            axis_names=_manual_axes(mesh),
            # check_vma=True is REQUIRED for partial-manual (see
            # pipeline_apply).
            check_vma=True,
        )(stack, nonstack, x_micro, tgt_micro)

    @jax.custom_vjp
    def sched(stack, nonstack, x_micro, tgt_micro):
        return run_schedule(stack, nonstack, x_micro, tgt_micro)[0]

    def sched_fwd(stack, nonstack, x_micro, tgt_micro):
        loss, dstack, dnon, dx = run_schedule(stack, nonstack, x_micro,
                                              tgt_micro)
        return loss, (dstack, dnon, dx)

    def sched_bwd(res, g):
        dstack, dnon, dx = res
        return (jax.tree.map(lambda v: v * g, dstack),
                jax.tree.map(lambda v: v * g, dnon),
                dx * g, None)

    sched.defvjp(sched_fwd, sched_bwd)

    def loss(params, batch, rng):
        tokens = batch["inputs"]
        b = tokens.shape[0]
        if b % micro:
            raise ValueError(
                f"batch {b} must divide into {micro} microbatches")
        mb = b // micro
        x = model.apply(params, tokens, method="embed_tokens")
        x_micro = x.astype(cfg.dtype).reshape((micro, mb) + x.shape[1:])
        tgt_micro = tokens.reshape((micro, mb) + tokens.shape[1:])
        nonstack = {k: v for k, v in params["params"].items()
                    if k != stack_root}
        stack = params["params"][stack_root]
        for key in stack_keys[1:]:
            stack = stack[key]
        l = sched(stack, nonstack, x_micro, tgt_micro)
        return l, {"perplexity": jnp.exp(l)}

    return loss
