"""Pipeline parallelism: GPipe-style schedule over the ``pp`` mesh axis.

The reference has no tensor-level pipeline support (SURVEY.md 2.12); here
stages live on mesh devices and activations move stage-to-stage with
``ppermute`` (one ICI hop on TPU).  The schedule is a single ``lax.scan``
over ``n_micro + n_stages - 1`` ticks: in steady state every stage
computes one microbatch per tick while the permute of the previous tick's
activations rides the ICI in parallel — XLA overlaps the two.

Assumes homogeneous stages (a stack of identical blocks — the transformer
case): each device holds its own stage's params; stage0 additionally owns
embedding, the last stage the head (handled by the caller's stage_fn via
the stage index).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import active_batch_axes


def _pipeline_shard(params, x_micro, *, axis_name: str, stage_fn,
                    n_micro: int):
    """Per-shard body.

    params:  this stage's params (pytree, local).
    x_micro: [n_micro, mb, ...] input microbatches (only stage 0's are
             real; other stages receive garbage they ignore).
    Returns [n_micro, mb, ...] outputs (valid on the LAST stage).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    buf_shape = x_micro.shape[1:]
    out_accum = jnp.zeros_like(x_micro)

    def tick(carry, t):
        carried_act, out_accum = carry
        # Stage 0 ingests microbatch t (while t < n_micro); other stages
        # consume what arrived from the left neighbor.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, 0,
                                              keepdims=False)
        x_in = jnp.where(stage == 0, inject, carried_act)
        y = stage_fn(stage, params, x_in)
        # Last stage writes its result for microbatch (t - n_stages + 1).
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = jnp.logical_and(stage == n_stages - 1,
                                t >= n_stages - 1)
        out_accum = jax.lax.cond(
            write,
            lambda acc: jax.lax.dynamic_update_index_in_dim(
                acc, y, out_idx, 0),
            lambda acc: acc,
            out_accum,
        )
        # Move activations right one stage for the next tick.
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return (nxt, out_accum), None

    init = (jnp.zeros(buf_shape, x_micro.dtype), out_accum)
    (_, out_accum), _ = jax.lax.scan(tick, init, jnp.arange(total))
    return out_accum


def pipeline_apply(
    stage_fn: Callable[[jax.Array, Any, jax.Array], jax.Array],
    params_stacked: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "pp",
    n_micro: int = 4,
    batch_axes=("dp", "fsdp"),
) -> jax.Array:
    """Run a homogeneous pipeline.

    stage_fn(stage_index, stage_params, x) -> y  (same shape as x).
    params_stacked: pytree whose leaves have a leading [n_stages] axis
    (stage i's slice lives on pipeline rank i).
    x: GLOBAL [batch, ...]; batch must divide n_micro * microbatch.
    Returns y with x's sharding; results are only meaningful after the
    caller reads them from the last stage (psum-broadcast below makes the
    value uniform across the pp axis so downstream code is simple).
    """
    from jax import shard_map

    n_stages = mesh.shape.get(axis_name, 1)
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(f"Batch {batch} must divide into {n_micro} microbatches")
    mb = batch // n_micro

    bspec = active_batch_axes(mesh, batch_axes)
    param_spec = jax.tree.map(lambda _: P(axis_name), params_stacked)
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    def body(params, xm):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        out = _pipeline_shard(params, xm, axis_name=axis_name,
                              stage_fn=stage_fn, n_micro=n_micro)
        # Broadcast the last stage's result to all pp ranks.
        n = jax.lax.psum(1, axis_name)
        stage = jax.lax.axis_index(axis_name)
        out = jnp.where(stage == n - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis_name)

    out_micro = shard_map(
        body, mesh=mesh,
        in_specs=(param_spec, P(None, bspec)),
        out_specs=P(None, bspec),
        check_vma=False,
    )(params_stacked, x_micro)
    return out_micro.reshape((batch,) + out_micro.shape[2:])


def pipelined_lm_loss(model, block, mesh, *, n_micro: int = 0,
                      stack_keys=("h", "block")):
    """Train-step loss that routes a scanned transformer's block stack
    through the ``pp`` pipeline (VERDICT r1 #5: ``strategy: {pp: N}``
    must mean something end-to-end).

    ``model`` decomposes via ``embed_tokens``/``head`` methods (embedding
    and head run on every pipeline rank — tiny next to the stack);
    ``block`` is one layer module whose stacked params live under
    ``params["params"][stack_keys...]`` with a leading [num_layers] axis
    (the nn.scan layout).  Stages rematerialize per layer when the model
    config asks for remat.
    """
    import jax.numpy as jnp
    import optax

    cfg = model.cfg
    n_stages = mesh.shape.get("pp", 1)
    if cfg.num_layers % max(n_stages, 1):
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide pp={n_stages}")
    per_stage = cfg.num_layers // max(n_stages, 1)
    micro = n_micro or 2 * n_stages

    def loss(params, batch, rng):
        tokens = batch["inputs"]
        x = model.apply(params, tokens, method="embed_tokens")

        stack = params["params"]
        for key in stack_keys:
            stack = stack[key]
        stacked = jax.tree.map(
            lambda p: p.reshape((n_stages, per_stage) + p.shape[1:]),
            stack)

        def one_layer(h, layer_params):
            return block.apply({"params": layer_params}, h), None

        body = jax.checkpoint(one_layer) if getattr(cfg, "remat", False) \
            else one_layer

        def stage_fn(stage_idx, stage_params, h):
            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        x = pipeline_apply(stage_fn, stacked, x.astype(cfg.dtype), mesh,
                           n_micro=micro)
        logits = model.apply(params, x, method="head")
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]).mean()
        return l, {"perplexity": jnp.exp(l)}

    return loss
