"""`jax.shard_map` compatibility for older jax (0.4.x).

The parallel layer is written against the modern surface —
``from jax import shard_map`` with ``check_vma=`` and (for
partial-manual pipelining) ``axis_names=``.  Older jax ships the same
machinery as ``jax.experimental.shard_map.shard_map`` with the
previous spellings: ``check_rep=`` and the COMPLEMENT parameter
``auto=`` (the axes left automatic) instead of ``axis_names`` (the
axes made manual).  This wrapper translates; every call site imports
it only when the top-level name is missing, so on modern jax the
real function runs untouched.
"""

from __future__ import annotations


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool = True, axis_names=None):
    from jax.experimental.shard_map import shard_map as _shard_map

    # check_rep is always OFF here: the old checker predates the VMA
    # system the callers' check_vma=True relies on (pipeline.py
    # promotes carries with pcast, which doesn't exist either) and
    # rejects valid cond/ppermute bodies.  The modern path keeps the
    # full check; this wrapper only exists where that path is
    # unavailable.
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) \
            - frozenset(axis_names)
    mapped = _shard_map(f, **kwargs)
    if kwargs.get("auto"):
        # Old shard_map's eager impl refuses partial-auto outright
        # (`if auto: raise NotImplementedError`); under jit it works.
        # A nested jit inlines, so already-jitted callers lose
        # nothing and eager callers (the multichip dryrun's pp leg)
        # gain the supported path.
        import jax

        mapped = jax.jit(mapped)
    return mapped
