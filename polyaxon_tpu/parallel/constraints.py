"""Activation sharding constraints (VERDICT r1 #2).

Parameter shardings alone let XLA pick activation layouts per-op; on
mixed dp×fsdp×tp meshes that produced "Involuntary full
rematerialization" — a per-step full-tensor copy whenever consecutive
ops disagreed on layout.  The fix is the standard GSPMD recipe: models
pin their activation layouts with ``with_sharding_constraint`` so
params and activations agree end-to-end.

Models don't know the mesh, so the train-step machinery publishes it as
an *ambient mesh* for the duration of tracing (a contextvar read at
trace time, zero runtime cost).  ``constrain`` is a no-op when no mesh
is ambient (single-device tests, plain ``model.apply``) and silently
drops axis names the mesh doesn't have — model code stays
strategy-agnostic.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional, Sequence, Tuple, Union

AxisName = Union[None, str, Sequence[str]]

_AMBIENT_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "ptpu_ambient_mesh", default=None)

# Serving-exact mesh (serving/meshed.py): a SECOND ambient channel
# with different semantics.  Training publishes the mesh so constrain
# SHARDS activations (the Megatron layout — fastest, but the row-
# parallel matmuls psum partial products, which reorders float
# accumulation).  The serving engine's contract is TOKEN-BITWISE
# equality to unmeshed execution, so under an exact mesh every
# constrain site that names a TENSOR axis ("tp"/"ep") instead forces
# the activation REPLICATED — an all-gather, which is pure
# concatenation — right before the row-parallel contraction that
# would otherwise psum.  The SPMD decomposition then contains no
# cross-device float reduction at all: column-parallel matmuls keep
# every output element's accumulation order, attention shards over
# heads (per-head math untouched), and gathers move bytes, never
# reassociate sums.  docs/SERVING.md "Meshed serving".
_EXACT_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "ptpu_serving_exact_mesh", default=None)

# The canonical batch-dim axes (matches mesh.active_batch_axes).
BATCH: Tuple[str, ...] = ("dp", "fsdp")

# Axes whose constrain sites sit immediately before a contraction
# over the constrained dim (o_proj/down_proj inputs, vocab logits):
# the exact mode's force-replicate points.
TENSOR_AXES: Tuple[str, ...] = ("tp", "ep")


@contextlib.contextmanager
def ambient_mesh(mesh):
    """Publish ``mesh`` to ``constrain`` calls traced inside the block."""
    token = _AMBIENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _AMBIENT_MESH.reset(token)


def current_mesh():
    return _AMBIENT_MESH.get()


@contextlib.contextmanager
def exact_mesh(mesh):
    """Publish ``mesh`` as the serving-exact mesh for traces inside
    the block (no-op when ``mesh`` is None).  Contextvar-scoped, so
    each caller wraps its own jit CALLS (tracing happens on first call)
    and concurrent meshed/unmeshed traces on other threads never see
    it."""
    if mesh is None:
        yield None
        return
    token = _EXACT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _EXACT_MESH.reset(token)


def current_exact_mesh():
    return _EXACT_MESH.get()


def constrain(x, *axes: AxisName):
    """``with_sharding_constraint`` against the ambient mesh.

    Each entry of ``axes`` is None, a mesh axis name, or a tuple of
    names for one dimension of ``x`` (align with ``x.ndim``; trailing
    dims may be omitted and stay unconstrained).  Names absent from the
    ambient mesh, or present with size 1, are dropped — so
    ``constrain(x, BATCH, None, "tp")`` is safe on any mesh.

    Under a serving-exact mesh (:func:`exact_mesh`) the semantics
    flip: a site naming a TENSOR axis forces the activation
    REPLICATED (the pre-contraction all-gather of the reduction-free
    serving layout), every other site is a no-op — bitwise equality
    to unmeshed execution, see the module-level note on _EXACT_MESH.
    """
    emesh = _EXACT_MESH.get()
    if emesh is not None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def _names(a):
            return (a,) if isinstance(a, str) else tuple(a or ())

        if any(n in TENSOR_AXES for a in axes for n in _names(a)):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(emesh, P()))
        return x
    mesh = _AMBIENT_MESH.get()
    if mesh is None:
        return x

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Inside shard_map the mesh axes are Manual and per-axis constraints
    # are illegal (and meaningless — the caller already laid data out);
    # models run under both jit (constrain) and shard_map (no-op), e.g.
    # blocks executing inside the pp pipeline.  Older jax (0.4.x) has
    # no get_abstract_mesh; there the probe is the bound named-axis
    # env — inside shard_map the mesh axes are bound, and the
    # resulting sharding error would surface at LOWERING, outside the
    # ValueError catch below, so it must be caught at trace time.
    if hasattr(jax.sharding, "get_abstract_mesh"):
        abstract = jax.sharding.get_abstract_mesh()
        if abstract is not None and any(
                "Manual" in str(t)
                for t in getattr(abstract, "axis_types", ())):
            return x
    else:
        try:
            from jax._src.core import get_axis_env

            if any(a in mesh.shape
                   for a in get_axis_env().axis_sizes):
                return x
        # Private-API drift on some other old jax: fall through to
        # the ValueError catch below (best-effort probe, per-trace-
        # call — logging here would spam every trace).
        except Exception:  # ptpu: ignore[EXC-SWALLOW]
            pass

    spec = []
    for a in axes:
        names = (a,) if isinstance(a, str) else tuple(a or ())
        names = tuple(n for n in names if mesh.shape.get(n, 1) > 1)
        spec.append(names if len(names) > 1
                    else (names[0] if names else None))
    ndim = getattr(x, "ndim", len(spec))
    spec = spec[:ndim] + [None] * (ndim - len(spec))
    if all(s is None for s in spec):
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))
    except ValueError:
        # Manual-axes contexts that the abstract-mesh probe missed
        # (e.g. shard_map traced under jit): constraints are layout
        # hints, never correctness — drop them rather than abort.
        return x
