"""Activation sharding constraints (VERDICT r1 #2).

Parameter shardings alone let XLA pick activation layouts per-op; on
mixed dp×fsdp×tp meshes that produced "Involuntary full
rematerialization" — a per-step full-tensor copy whenever consecutive
ops disagreed on layout.  The fix is the standard GSPMD recipe: models
pin their activation layouts with ``with_sharding_constraint`` so
params and activations agree end-to-end.

Models don't know the mesh, so the train-step machinery publishes it as
an *ambient mesh* for the duration of tracing (a contextvar read at
trace time, zero runtime cost).  ``constrain`` is a no-op when no mesh
is ambient (single-device tests, plain ``model.apply``) and silently
drops axis names the mesh doesn't have — model code stays
strategy-agnostic.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional, Sequence, Tuple, Union

AxisName = Union[None, str, Sequence[str]]

_AMBIENT_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "ptpu_ambient_mesh", default=None)

# The canonical batch-dim axes (matches mesh.active_batch_axes).
BATCH: Tuple[str, ...] = ("dp", "fsdp")


@contextlib.contextmanager
def ambient_mesh(mesh):
    """Publish ``mesh`` to ``constrain`` calls traced inside the block."""
    token = _AMBIENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _AMBIENT_MESH.reset(token)


def current_mesh():
    return _AMBIENT_MESH.get()


def constrain(x, *axes: AxisName):
    """``with_sharding_constraint`` against the ambient mesh.

    Each entry of ``axes`` is None, a mesh axis name, or a tuple of
    names for one dimension of ``x`` (align with ``x.ndim``; trailing
    dims may be omitted and stay unconstrained).  Names absent from the
    ambient mesh, or present with size 1, are dropped — so
    ``constrain(x, BATCH, None, "tp")`` is safe on any mesh.
    """
    mesh = _AMBIENT_MESH.get()
    if mesh is None:
        return x

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Inside shard_map the mesh axes are Manual and per-axis constraints
    # are illegal (and meaningless — the caller already laid data out);
    # models run under both jit (constrain) and shard_map (no-op), e.g.
    # blocks executing inside the pp pipeline.
    abstract = jax.sharding.get_abstract_mesh()
    if abstract is not None and any(
            "Manual" in str(t)
            for t in getattr(abstract, "axis_types", ())):
        return x

    spec = []
    for a in axes:
        names = (a,) if isinstance(a, str) else tuple(a or ())
        names = tuple(n for n in names if mesh.shape.get(n, 1) > 1)
        spec.append(names if len(names) > 1
                    else (names[0] if names else None))
    ndim = getattr(x, "ndim", len(spec))
    spec = spec[:ndim] + [None] * (ndim - len(spec))
    if all(s is None for s in spec):
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))
    except ValueError:
        # Manual-axes contexts that the abstract-mesh probe missed
        # (e.g. shard_map traced under jit): constraints are layout
        # hints, never correctness — drop them rather than abort.
        return x
