"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

Second context-parallel strategy (SURVEY.md 5.7): instead of rotating K/V
(ring), reshard so each device sees the FULL sequence for a subset of
heads — one all-to-all before attention, one after.  On TPU the
``all_to_all`` lowers to ICI all-to-all; cost is 2 reshards of activations
vs the ring's (n-1) K/V hops, favoring Ulysses when heads >> sp and
attention kernels want the whole sequence (e.g. flash attention on-chip).

Requires num_heads % sp == 0.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import active_batch_axes


def _ulysses_shard(q, k, v, mask, *, axis_name: str, attn_fn):
    """Per-shard body: inputs [B, S/sp, H, D] -> output [B, S/sp, H, D].

    ``mask``: None or boolean [B, H?, Sq, Sk] replicated across the sp
    axis (full sequence dims); when it carries a real head dim, each
    rank slices its own head range after the all-to-all.
    """

    def seq2head(x):
        # [B, S/sp, H, D] -> [B, S, H/sp, D]: split heads, gather sequence.
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head2seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    q_full = seq2head(q)
    k_full = seq2head(k)
    v_full = seq2head(v)
    if mask is None:
        # Unmasked: keep the original 3-arg attn_fn contract so existing
        # custom kernels (attn_fn=lambda q, k, v: ...) stay valid.
        o_full = attn_fn(q_full, k_full, v_full)
    else:
        mask_local = mask
        if mask.shape[1] > 1:
            n = jax.lax.psum(1, axis_name)
            idx = jax.lax.axis_index(axis_name)
            h_per = mask.shape[1] // n
            mask_local = jax.lax.dynamic_slice_in_dim(
                mask, idx * h_per, h_per, axis=1)
        o_full = attn_fn(q_full, k_full, v_full, mask_local)
    return head2seq(o_full)


def _default_inner(q, k, v, mask=None, *, causal: bool,
                   scale: Optional[float], window: Optional[int] = None):
    """Per-shard attention after the all-to-all: each rank holds the
    FULL sequence for a head subset — exactly the flash kernel's shape,
    so route through it when eligible (TPU or the interpret-mode tests,
    lane-aligned seq, MXU-aligned head dim, at most a key-padding
    mask); otherwise the fused-XLA fallback."""
    from ..ops.flash import flash_attention, flash_eligible, \
        narrow_kv_mask

    if flash_eligible(q.shape[1], k.shape[1], q.shape[-1], mask):
        kvm = None if mask is None else \
            narrow_kv_mask(mask, q.shape[0], k.shape[1])
        return flash_attention(
            q, k, v, causal=causal,
            scale=q.shape[-1] ** -0.5 if scale is None else scale,
            kv_mask=kvm, window=window)
    return _plain_attention(q, k, v, mask, causal=causal, scale=scale,
                            window=window)


def _plain_attention(q, k, v, mask=None, *, causal: bool,
                     scale: Optional[float],
                     window: Optional[int] = None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:  # window implies causal (validated at every driver)
        # Post-all-to-all each rank holds the FULL sequence, so local
        # indices ARE global positions; the window composes directly.
        sq, sk = q.shape[1], k.shape[1]
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(sk)[None, :]
        cmask = qi >= ki
        if window is not None:
            cmask &= qi - ki <= window
        scores = jnp.where(cmask[None, :, None, :], scores, -1e30)
    if mask is not None:
        # [B, H?, Sq, Sk] -> scores' [B, Sq, H, Sk]
        scores = jnp.where(jnp.transpose(mask, (0, 2, 1, 3)),
                           scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    mask: Optional[jax.Array] = None,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    attn_fn: Optional[Callable] = None,
    batch_axes=("dp", "fsdp"),
):
    """Ulysses attention over a mesh axis; q/k/v GLOBAL [B, S, H, D].

    ``mask``: optional boolean [B, H?, Sq, Sk] (True = attend; padded
    batches keep sequence parallelism — VERDICT r1 #8).  The mask's
    sequence dims stay full (post-all-to-all each rank sees the whole
    sequence); a real head dim must divide the sp axis like q's.

    ``attn_fn``: custom kernel called as ``attn_fn(q, k, v)`` when no
    mask is given (the original contract) and ``attn_fn(q, k, v, mask)``
    when one is — a 3-arg kernel stays valid for unmasked use.
    """
    try:
        from jax import shard_map
    except ImportError:   # older jax: translated spellings
        from ._shard_map_compat import shard_map

    sp = mesh.shape.get(axis_name, 1)
    n_heads = q.shape[2]
    if n_heads % sp:
        raise ValueError(
            f"Ulysses needs heads ({n_heads}) divisible by {axis_name} "
            f"axis size ({sp}); use ring attention otherwise"
        )
    if mask is not None:
        if mask.ndim != 4:
            raise ValueError(
                f"mask must be 4-d [B,H,Sq,Sk]; got {mask.shape}")
        if mask.shape[1] > 1 and mask.shape[1] % sp:
            raise ValueError(
                f"mask head dim ({mask.shape[1]}) must divide sp ({sp})")
    if window is not None:
        if not causal:
            raise ValueError("sliding window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1; got {window}")
        if attn_fn is not None:
            raise ValueError(
                "window with a custom attn_fn would be silently "
                "ignored; apply the window inside your kernel instead")
    inner = attn_fn or functools.partial(_default_inner, causal=causal,
                                         scale=scale, window=window)
    batch = active_batch_axes(mesh, batch_axes)
    spec = P(batch, axis_name, None, None)
    body = functools.partial(_ulysses_shard, axis_name=axis_name,
                             attn_fn=inner)
    if mask is None:
        return shard_map(
            lambda q, k, v: body(q, k, v, None), mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    mask_spec = P(batch if mask.shape[0] > 1 else None, None, None, None)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, mask)
