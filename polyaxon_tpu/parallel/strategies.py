"""Strategy library: build sharded train steps from a spec.

This is the framework-owned replacement for the reference's delegated
DP-via-NCCL / ring-allreduce paths (SURVEY.md 2.12/5.8):

- **DP**:   batch sharded over ``dp``; XLA inserts the gradient AllReduce
            (ICI within a slice, hierarchical over DCN for multi-slice
            meshes) and overlaps it with the backward pass.
- **FSDP**: params/optimizer sharded on their largest axis over ``fsdp``;
            XLA turns the weight use into all-gather + reduce-scatter.
- **TP**:   params matching the tensor-parallel rules shard over ``tp``.
- Strategies compose: one mesh, one set of PartitionSpecs.

The job spec selects a strategy via ``run.strategy`` (e.g.
``{dp: -1, tp: 4}``) — see ``flow.run.V1TPUJob.strategy``.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MeshSpec, build_mesh, data_sharding


# Rules: (regex over the param path, PartitionSpec builder).  First match
# wins.  Paths look like "transformer/layers_3/attn/qkv/kernel".
TP_RULES: List[Tuple[str, Callable[[tuple], P]]] = [
    # Row-parallel (input dim sharded) rules first — they are the more
    # specific names and must win over any generic block-name token.
    (r"(o_proj|out_proj|attention_out|proj_out)[^/]*/kernel",
     lambda shape: P("tp", None)),
    (r"(fc2|wo|down_proj|output_dense|mlp_out)[^/]*/kernel",
     lambda shape: P("tp", None)),
    # Column-parallel (output dim sharded).
    (r"(q_proj|k_proj|v_proj|qkv|query|key|value)[^/]*/kernel",
     lambda shape: P(None, "tp")),
    (r"(fc1|wi|up_proj|gate_proj|intermediate)[^/]*/kernel",
     lambda shape: P(None, "tp")),
    # Embeddings / LM head: shard the vocab dim.
    (r"(embed|embedding|wte|lm_head)[^/]*/(embedding|kernel)",
     lambda shape: P("tp", None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None) or getattr(p, "name", None) or \
            getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts)


def infer_param_spec(
    path,
    leaf,
    *,
    tp: bool = False,
    fsdp: bool = False,
    fsdp_min_size: int = 2 ** 16,
) -> P:
    """PartitionSpec for one parameter."""
    shape = getattr(leaf, "shape", ())
    spec = [None] * len(shape)
    name = _path_str(path)

    if tp:
        for pattern, builder in TP_RULES:
            if re.search(pattern, name):
                cand = builder(shape)
                cand_list = list(cand) + [None] * (len(shape) - len(cand))
                spec = cand_list[:len(shape)]
                break

    if fsdp and int(np.prod(shape or (1,))) >= fsdp_min_size:
        # Shard the largest still-unsharded axis over fsdp.
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for axis in order:
            if spec[axis] is None:
                spec[axis] = "fsdp"
                break
    return P(*spec)


def make_param_shardings(
    params: Any,
    mesh: Mesh,
    *,
    fsdp_min_size: int = 2 ** 16,
) -> Any:
    """NamedShardings for a param pytree based on the mesh's active axes."""
    tp = mesh.shape.get("tp", 1) > 1
    fsdp = mesh.shape.get("fsdp", 1) > 1

    def leaf_sharding(path, leaf):
        spec = infer_param_spec(path, leaf, tp=tp, fsdp=fsdp,
                                fsdp_min_size=fsdp_min_size)
        # Drop axes that don't divide the dim.
        shape = getattr(leaf, "shape", ())
        fixed = []
        for dim, ax in zip(shape, spec):
            if ax is not None and dim % mesh.shape[ax] != 0:
                ax = None
            fixed.append(ax)
        return NamedSharding(mesh, P(*fixed))

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return data_sharding(mesh)


class TrainStep:
    """A compiled, sharded train step.

    Wraps: loss_fn(params, batch, rng) -> (loss, aux) into
    step(state, batch, rng) -> (state, metrics), jitted over the mesh with
    donated state.  ``state`` is a dict {params, opt_state, step}.
    """

    def __init__(
        self,
        loss_fn: Callable,
        optimizer,
        mesh: Mesh,
        *,
        param_shardings=None,
        batch_sharding=None,
        donate: bool = True,
        grad_accum: int = 1,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.param_shardings = param_shardings
        self.batch_sharding = batch_sharding or make_batch_sharding(mesh)
        self.grad_accum = grad_accum
        self._step = None
        self._donate = donate

    def init_state(self, params) -> Dict[str, Any]:
        shardings = self.param_shardings or make_param_shardings(params,
                                                                 self.mesh)
        self.param_shardings = shardings
        params = jax.device_put(params, shardings)
        opt_state = jax.jit(
            self.optimizer.init,
            out_shardings=None,  # let XLA lay optimizer state like params
        )(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    def _build(self):
        loss_fn, optimizer = self.loss_fn, self.optimizer
        accum = self.grad_accum

        def one_grad(params, batch, rng):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, rng)
            return loss, aux, grads

        def step(state, batch, rng):
            params = state["params"]
            if accum > 1:
                def micro(carry, inp):
                    mb, idx = inp
                    loss_a, grads_a = carry
                    # Each microbatch gets an independent rng (dropout /
                    # MLM masks must differ across microbatches).
                    r = None if rng is None else jax.random.fold_in(rng,
                                                                    idx)
                    loss, aux, grads = one_grad(params, mb, r)
                    grads_a = jax.tree.map(jnp.add, grads_a, grads)
                    return (loss_a + loss, grads_a), aux
                micro_batches = jax.tree.map(
                    lambda x: x.reshape((accum, x.shape[0] // accum)
                                        + x.shape[1:]), batch)
                zeros = jax.tree.map(jnp.zeros_like, params)
                (loss, grads), aux = jax.lax.scan(
                    micro, (jnp.zeros(()), zeros),
                    (micro_batches, jnp.arange(accum)))
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
                # aux is stacked [accum, ...]: average so metrics describe
                # the whole batch, not just the last microbatch.
                aux = jax.tree.map(lambda a: a.mean(0), aux)
            else:
                loss, aux, grads = one_grad(params, batch, rng)
            # Mutable model state (e.g. BN running stats) rides aux under
            # a reserved key and is merged back into params, not metrics.
            new_vars = None
            if isinstance(aux, dict) and "__new_vars__" in aux:
                aux = dict(aux)
                new_vars = aux.pop("__new_vars__")
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], params)
            params = jax.tree.map(
                lambda p, u: (p + u).astype(p.dtype), params, updates)
            if new_vars is not None:
                params = {**params, **new_vars}
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **(aux or {})}
            return (
                {"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                metrics,
            )

        self._step = jax.jit(
            step,
            donate_argnums=(0,) if self._donate else (),
            in_shardings=(None, self.batch_sharding, None),
        )
        return self._step

    def __call__(self, state, batch, rng):
        if self._step is None:
            self._build()
        return self._step(state, batch, rng)


def make_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Optional[Mesh] = None,
    spec: Optional[MeshSpec] = None,
    **kwargs,
) -> TrainStep:
    mesh = mesh or build_mesh(spec)
    return TrainStep(loss_fn, optimizer, mesh, **kwargs)
