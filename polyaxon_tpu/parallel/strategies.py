"""Strategy library: build sharded train steps from a spec.

This is the framework-owned replacement for the reference's delegated
DP-via-NCCL / ring-allreduce paths (SURVEY.md 2.12/5.8):

- **DP**:   batch sharded over ``dp``; XLA inserts the gradient AllReduce
            (ICI within a slice, hierarchical over DCN for multi-slice
            meshes) and overlaps it with the backward pass.
- **FSDP**: params/optimizer sharded on their largest axis over ``fsdp``;
            XLA turns the weight use into all-gather + reduce-scatter.
- **TP**:   params matching the tensor-parallel rules shard over ``tp``.
- Strategies compose: one mesh, one set of PartitionSpecs.

The job spec selects a strategy via ``run.strategy`` (e.g.
``{dp: -1, tp: 4}``) — see ``flow.run.V1TPUJob.strategy``.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .constraints import ambient_mesh
from .mesh import MeshSpec, build_mesh, data_sharding


# Rules: (regex over the param path, PartitionSpec builder).  First match
# wins.  Paths look like "transformer/layers_3/attn/qkv/kernel".
TP_RULES: List[Tuple[str, Callable[[tuple], P]]] = [
    # Row-parallel (input dim sharded) rules first — they are the more
    # specific names and must win over any generic block-name token.
    (r"(o_proj|out_proj|attention_out|proj_out)[^/]*/kernel",
     lambda shape: P("tp", None)),
    (r"(fc2|wo|down_proj|output_dense|mlp_out)[^/]*/kernel",
     lambda shape: P("tp", None)),
    # Column-parallel (output dim sharded).
    (r"(q_proj|k_proj|v_proj|qkv|query|key|value)[^/]*/kernel",
     lambda shape: P(None, "tp")),
    (r"(fc1|wi|up_proj|gate_proj|intermediate)[^/]*/kernel",
     lambda shape: P(None, "tp")),
    # Untied LM head (a Dense, kernel [hidden, vocab]): vocab is the
    # OUTPUT axis — must outrank the embedding rule below, whose axis-0
    # vocab convention would shard the hidden dim here.
    (r"lm_head[^/]*/kernel",
     lambda shape: P(None, ("tp", "fsdp"))),
    # Embeddings (tables [vocab, hidden]): shard the vocab dim over BOTH
    # tp and fsdp (axes of size 1 are no-ops).  Sharding the hidden dim
    # instead makes every token lookup emit a hidden-sharded [B,S,H]
    # that XLA can only reconcile with the batch-sharded residual stream
    # by replicating the whole tensor (involuntary full
    # rematerialization).
    (r"(embed|embedding|wte)[^/]*/embedding",
     lambda shape: P(("tp", "fsdp"), None)),
    # Expert-parallel params [E, in, out]: shard the expert dim over ep —
    # the layout moe_layer's shard_map expects, so no reshard precedes
    # the all-to-all dispatch.
    (r"experts_w[12]$",
     lambda shape: P("ep", None, None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None) or getattr(p, "name", None) or \
            getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts)


# Scan-stacked block params ("h/block/...", "layers/layer/...") carry a
# leading [num_layers] axis; with pipeline parallelism each stage's
# slice of that axis lives on its pipeline rank.
_STACK_RE = re.compile(r"(^|/)(h|layers)/")


def infer_param_spec(
    path,
    leaf,
    *,
    tp: bool = False,
    fsdp: bool = False,
    pp: bool = False,
    ep: bool = False,
    fsdp_min_size: int = 2 ** 16,
) -> P:
    """PartitionSpec for one parameter."""
    shape = getattr(leaf, "shape", ())
    spec = [None] * len(shape)
    name = _path_str(path)

    # The rule table carries both tp- and ep-named axes; names whose
    # mesh axis has size 1 are no-ops, so running the table when either
    # axis is active is safe.
    if tp or ep:
        for pattern, builder in TP_RULES:
            if re.search(pattern, name):
                cand = list(builder(shape))
                # Right-align: rules describe the TRAILING (in, out) dims
                # so scanned/stacked params ([layers, in, out]) shard the
                # same way as flat ones — never the layer axis.
                if len(cand) <= len(shape):
                    spec = [None] * (len(shape) - len(cand)) + cand
                else:
                    spec = cand[len(cand) - len(shape):]
                break

    if pp and len(shape) >= 2 and spec[0] is None and \
            _STACK_RE.search(name):
        spec[0] = "pp"

    def _names(entry):
        return entry if isinstance(entry, tuple) else \
            ((entry,) if entry else ())

    fsdp_taken = any("fsdp" in _names(s) for s in spec)
    if fsdp and not fsdp_taken and \
            int(np.prod(shape or (1,))) >= fsdp_min_size:
        # Shard the largest still-unsharded axis over fsdp, preferring
        # the trailing two dims (the matmul dims): a scan-stacked layer
        # axis is a poor fsdp axis (it would gather all layers at once).
        matmul_dims = [i for i in range(max(0, len(shape) - 2), len(shape))]
        lead_dims = [i for i in range(len(shape)) if i not in matmul_dims]
        order = sorted(matmul_dims, key=lambda i: -shape[i]) + \
            sorted(lead_dims, key=lambda i: -shape[i])
        for axis in order:
            if spec[axis] is None:
                spec[axis] = "fsdp"
                break
    return P(*spec)


def make_param_shardings(
    params: Any,
    mesh: Mesh,
    *,
    fsdp_min_size: int = 2 ** 16,
) -> Any:
    """NamedShardings for a param pytree based on the mesh's active axes."""
    tp = mesh.shape.get("tp", 1) > 1
    fsdp = mesh.shape.get("fsdp", 1) > 1
    pp = mesh.shape.get("pp", 1) > 1
    ep = mesh.shape.get("ep", 1) > 1

    def leaf_sharding(path, leaf):
        spec = infer_param_spec(path, leaf, tp=tp, fsdp=fsdp, pp=pp,
                                ep=ep, fsdp_min_size=fsdp_min_size)
        # Drop axes that don't divide the dim (tuple entries shrink
        # greedily from the right until the product divides).
        shape = getattr(leaf, "shape", ())
        fixed = []
        for dim, ax in zip(shape, spec):
            names = ax if isinstance(ax, tuple) else \
                ((ax,) if ax else ())
            while names and dim % int(np.prod(
                    [mesh.shape[n] for n in names])) != 0:
                names = names[:-1]
            fixed.append(names if len(names) > 1
                         else (names[0] if names else None))
        return NamedSharding(mesh, P(*fixed))

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return data_sharding(mesh)


# Every TrainStep that has BUILT its jitted/AOT step.  Sequence-parallel
# activation (ops/attention.py) consults this: a step traced before
# activation keeps its cached local-attention trace, so flipping the
# thread-local after a build would silently train without SP (VERDICT
# r2 weak #5 / r3 weak #3).
import weakref

_BUILT_STEPS: "weakref.WeakSet[TrainStep]" = weakref.WeakSet()


def compiled_step_count() -> int:
    """How many live TrainSteps hold a built (jitted or AOT) step fn."""
    return sum(1 for s in _BUILT_STEPS if s._step is not None)


class TrainStep:
    """A compiled, sharded train step.

    Wraps: loss_fn(params, batch, rng) -> (loss, aux) into
    step(state, batch, rng) -> (state, metrics), jitted over the mesh with
    donated state.  ``state`` is a dict {params, opt_state, step}.
    """

    def __init__(
        self,
        loss_fn: Callable,
        optimizer,
        mesh: Mesh,
        *,
        param_shardings=None,
        batch_sharding=None,
        donate: bool = True,
        grad_accum: int = 1,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.param_shardings = param_shardings
        self.batch_sharding = batch_sharding or make_batch_sharding(mesh)
        self.grad_accum = grad_accum
        self._step = None
        self._donate = donate

    def init_state(self, params) -> Dict[str, Any]:
        shardings = self.param_shardings or make_param_shardings(params,
                                                                 self.mesh)
        self.param_shardings = shardings
        params = jax.device_put(params, shardings)
        # Optimizer state must be laid out exactly like the params it
        # mirrors (adam mu/nu reuse the param subtree paths, so the same
        # rule function yields the same specs); XLA-chosen layouts here
        # caused involuntary-remat copies every step (VERDICT r1 #2).
        opt_shapes = jax.eval_shape(self.optimizer.init, params)
        opt_shardings = make_param_shardings(opt_shapes, self.mesh)
        opt_state = jax.jit(
            self.optimizer.init, out_shardings=opt_shardings)(params)
        from jax.sharding import NamedSharding

        self.state_shardings = {
            "params": shardings,
            "opt_state": opt_shardings,
            "step": NamedSharding(self.mesh, P()),
        }
        # The step counter must be COMMITTED to its NamedSharding, not
        # left as an uncommitted single-device scalar: an AOT-compiled
        # step (precompile) auto-moves uncommitted args, but a
        # checkpoint restored through this state as template yields a
        # committed SingleDeviceSharding scalar that the executable
        # hard-rejects — the round-3 preemption-resume regression.
        step0 = jax.device_put(jnp.zeros((), jnp.int32),
                               self.state_shardings["step"])
        return {"params": params, "opt_state": opt_state, "step": step0}

    def _build(self):
        loss_fn, optimizer = self.loss_fn, self.optimizer
        accum = self.grad_accum

        def one_grad(params, batch, rng):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, rng)
            return loss, aux, grads

        def step(state, batch, rng):
            params = state["params"]
            if accum > 1:
                def micro(carry, inp):
                    mb, idx = inp
                    loss_a, grads_a = carry
                    # Each microbatch gets an independent rng (dropout /
                    # MLM masks must differ across microbatches).
                    r = None if rng is None else jax.random.fold_in(rng,
                                                                    idx)
                    loss, aux, grads = one_grad(params, mb, r)
                    grads_a = jax.tree.map(jnp.add, grads_a, grads)
                    return (loss_a + loss, grads_a), aux
                micro_batches = jax.tree.map(
                    lambda x: x.reshape((accum, x.shape[0] // accum)
                                        + x.shape[1:]), batch)
                zeros = jax.tree.map(jnp.zeros_like, params)
                (loss, grads), aux = jax.lax.scan(
                    micro, (jnp.zeros(()), zeros),
                    (micro_batches, jnp.arange(accum)))
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
                # aux is stacked [accum, ...]: average so metrics describe
                # the whole batch, not just the last microbatch.
                aux = jax.tree.map(lambda a: a.mean(0), aux)
            else:
                loss, aux, grads = one_grad(params, batch, rng)
            # Mutable model state (e.g. BN running stats) rides aux under
            # a reserved key and is merged back into params, not metrics.
            new_vars = None
            if isinstance(aux, dict) and "__new_vars__" in aux:
                aux = dict(aux)
                new_vars = aux.pop("__new_vars__")
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], params)
            params = jax.tree.map(
                lambda p, u: (p + u).astype(p.dtype), params, updates)
            if new_vars is not None:
                params = {**params, **new_vars}
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **(aux or {})}
            return (
                {"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                metrics,
            )

        # Pin the state layout on BOTH sides of the step: with free output
        # shardings XLA may choose layouts for the updated params/opt
        # state that disagree with the input layout, forcing a full
        # copy-and-reshard every step (the involuntary-remat class of
        # VERDICT r1 #2).  state_shardings exists once init_state ran,
        # which all framework paths do before stepping.
        state_shardings = getattr(self, "state_shardings", None)
        self._step = jax.jit(
            step,
            donate_argnums=(0,) if self._donate else (),
            in_shardings=(state_shardings, self.batch_sharding, None),
            out_shardings=(state_shardings, None),
        )
        _BUILT_STEPS.add(self)
        return self._step

    def precompile(self, state, batch, rng):
        """AOT-compile the step for these shapes; reuse the executable.

        ``rng`` must be EXACTLY what later ``__call__``s will pass (a
        PRNG key, or None for rng-free losses): the installed
        executable is specialized to that argument structure, so
        compiling with None and stepping with a key would fail with an
        argument-mismatch error.

        ``lower().compile()`` does not share jit's in-process cache, so
        the compiled executable is installed as the step to avoid a
        second multi-minute XLA compile (gpt2-medium on the tunnel).
        Returns ``(compiled, compile_seconds)``; ``compiled
        .cost_analysis()`` describes the post-SPMD per-device module.
        This is the supported AOT surface — callers must not poke
        ``_step`` directly (VERDICT r2 weak #6).
        """
        import time

        jitted = self._build()
        t0 = time.perf_counter()
        # Activation `constrain` calls inside the model resolve against
        # the ambient mesh at trace time (constraints.py).
        with ambient_mesh(self.mesh):
            compiled = jitted.lower(state, batch, rng).compile()
        compile_s = time.perf_counter() - t0
        self._step = compiled
        return compiled, compile_s

    def __call__(self, state, batch, rng):
        if self._step is None:
            self._build()
        # Tracing happens on the first call: publish the mesh so model
        # activation `constrain` calls resolve against it (constraints.py).
        with ambient_mesh(self.mesh):
            try:
                return self._step(state, batch, rng)
            except (TypeError, ValueError) as e:
                # An AOT executable (precompile) is pinned to the exact
                # arg shapes/dtypes/shardings it was lowered for and,
                # unlike jit, cannot re-specialize.  The recoverable
                # drift is layout drift — args committed to the wrong
                # devices (a checkpoint restored without sharding
                # info).  Reshard onto the compiled layout and retry
                # the SAME executable: no recompile.  Shape/dtype
                # drift is a contract violation (__call__ args must
                # match precompile's) and re-raises.
                if not hasattr(self._step, "call"):
                    raise  # plain jit: a real error, not a pinned-AOT one
                shardings = getattr(self, "state_shardings", None)
                # Only a sharding disagreement is recoverable by a
                # reshard; shape/dtype drift would fail identically
                # after paying a full-state device copy.
                if shardings is None or \
                        "compiled for input shardings" not in str(e):
                    raise
                import logging

                logging.getLogger(__name__).warning(
                    "AOT step rejected args (%s); resharding onto the "
                    "compiled layout and retrying", e)
                state = jax.device_put(state, shardings)
                batch = jax.device_put(batch, self.batch_sharding)
                return self._step(state, batch, rng)


def make_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Optional[Mesh] = None,
    spec: Optional[MeshSpec] = None,
    **kwargs,
) -> TrainStep:
    mesh = mesh or build_mesh(spec)
    return TrainStep(loss_fn, optimizer, mesh, **kwargs)
