"""Ring attention: sequence/context parallelism over the ICI torus.

Long-context capability the reference lacks entirely (SURVEY.md 5.7).
Design follows the blockwise/ring-attention literature (see PAPERS.md):
each device owns one sequence block of Q/K/V; K/V blocks rotate around the
``sp`` axis via ``ppermute`` (on TPU this maps onto nearest-neighbor ICI
hops — the hardware *is* the ring), while each device accumulates its
local Q's attention with a numerically-stable running log-sum-exp.
Compute of block r overlaps with the DMA of block r+1 (XLA schedules the
ppermute async); the attention never materializes the full [S, S] matrix.

All functions are written per-shard and meant to be wrapped by
``shard_map`` (see ``ring_attention`` for the driver).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import active_batch_axes

BIG_NEG = -1e30


def _ring_flash_eligible(q, s_blk: int, mask) -> bool:
    """Static routing: run per-rotation blocks through the pallas flash
    kernel?  Shared predicate; the kernels see s_blk-length q/kv blocks
    while the key-padding mask keeps FULL kv columns (sliced per
    rotation), hence mask_kv_len."""
    from ..ops.flash import flash_eligible

    return flash_eligible(s_blk, s_blk, q.shape[-1], mask,
                          mask_kv_len=q.shape[1])


def _block_attend(q, k, v, *, scale, q_offset, kv_offset, causal,
                  mask_blk=None, window=None):
    """One blockwise attention contribution.

    q: [B, Sq, H, D], k/v: [B, Sk, H, D] -> (scores-derived partials)
    Returns (p @ v) unnormalized [B, Sq, H, D], row max m [B, Sq, H],
    row sum l [B, Sq, H] — all in f32 for stable accumulation.
    ``mask_blk``: optional boolean broadcastable to [B, H, Sq, Sk]
    (True = attend) covering exactly this KV block.
    """
    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q32, k32) * scale  # [B,Sq,H,Sk]
    if causal:  # window implies causal (validated at every driver)
        sq, sk = q.shape[1], k.shape[1]
        q_ids = q_offset + jnp.arange(sq)[:, None]
        k_ids = kv_offset + jnp.arange(sk)[None, :]
        mask = q_ids >= k_ids  # [Sq, Sk]
        if window is not None:
            mask &= q_ids - k_ids <= window
        scores = jnp.where(mask[None, :, None, :], scores, BIG_NEG)
    if mask_blk is not None:
        # [B, H, Sq, Sk] (broadcast dims allowed) -> scores' B,Sq,H,Sk.
        scores = jnp.where(jnp.transpose(mask_blk, (0, 2, 1, 3)),
                           scores, BIG_NEG)
    m = jnp.max(scores, axis=-1)  # [B,Sq,H]
    p = jnp.exp(scores - m[..., None])
    # Fully-masked rows: zero contribution (m stays BIG_NEG, p -> 1.0 rows
    # must not pollute the sum).
    valid = m > BIG_NEG / 2
    p = jnp.where(valid[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,Sq,H]
    pv = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return pv, m, l


def _ring_attention_shard(q, k, v, mask, *, axis_name: str, causal: bool,
                          scale: Optional[float], axis_size: int,
                          use_flash: bool = False, window=None):
    """Per-shard body: q/k/v are the LOCAL sequence blocks [B, Sblk, H, D].

    ``mask``: None, or boolean with kv dim FULL-length (each shard holds
    its q-rows but every key column, so each rotation slices the arriving
    block's columns out of it): broadcastable to [B, H, Sq_blk, S_full].

    ``use_flash``: run each block contribution through the pallas flash
    kernel (MXU path; decided statically by the driver) and combine the
    normalized per-block outputs exactly via their logsumexp:
    o = sum_r o_r * exp(lse_r - lse_total).  Future blocks of a causal
    ring skip their kernels entirely (lax.switch), which is where ring
    attention's causal FLOP saving actually materializes.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = axis_size
    my_idx = jax.lax.axis_index(axis_name)
    s_blk = q.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]
    if use_flash:
        return _ring_flash_shard(q, k, v, mask, scale=scale, causal=causal,
                                 n=n, my_idx=my_idx, perm=perm,
                                 axis_name=axis_name, window=window)

    def attend(acc, k_cur, v_cur, r):
        o, m, l = acc
        src = (my_idx - r) % n  # which block k_cur/v_cur originated from
        mask_blk = None
        if mask is not None:
            kv_len = k_cur.shape[1]
            if mask.shape[-1] in (1, kv_len):
                mask_blk = mask  # broadcast kv, or per-block (sp == 1)
            else:
                mask_blk = jax.lax.dynamic_slice_in_dim(
                    mask, src * s_blk, kv_len, axis=3)
        pv, m_blk, l_blk = _block_attend(
            q, k_cur, v_cur, scale=scale,
            q_offset=my_idx * s_blk, kv_offset=src * s_blk, causal=causal,
            mask_blk=mask_blk, window=window,
        )
        new_m = jnp.maximum(m, m_blk)
        corr_old = jnp.exp(m - new_m)
        corr_new = jnp.exp(m_blk - new_m)
        # exp(BIG_NEG - BIG_NEG) = 1 on never-touched rows: guard with the
        # validity of each side instead.
        corr_old = jnp.where(m > BIG_NEG / 2, corr_old, 0.0)
        corr_new = jnp.where(m_blk > BIG_NEG / 2, corr_new, 0.0)
        o = o * corr_old[..., None] + pv * corr_new[..., None]
        l = l * corr_old + l_blk * corr_new
        return o, new_m, l

    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:2] + q.shape[2:3], BIG_NEG, jnp.float32)  # [B,Sq,H]
    l = jnp.zeros(q.shape[:2] + q.shape[2:3], jnp.float32)

    def step(carry, r):
        o, m, l, k_cur, v_cur = carry
        o, m, l = attend((o, m, l), k_cur, v_cur, r)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    # n-1 rotations only: the last block is consumed without a further
    # ppermute (it would be dead ICI traffic on every forward).
    k_cur, v_cur = k, v
    if n > 1:
        (o, m, l, k_cur, v_cur), _ = jax.lax.scan(
            step, (o, m, l, k, v), jnp.arange(n - 1))
    o, m, l = attend((o, m, l), k_cur, v_cur, n - 1)

    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros, not NaN
    return (o / l[..., None]).astype(q.dtype)


def _ring_flash_shard(q, k, v, mask, *, scale, causal, n, my_idx, perm,
                      axis_name, window=None):
    """Flash-kernel ring body.  ``mask`` here is None or a key-padding
    mask [B, S_full] bool (the driver narrows the 4-d form).

    ``window`` (sliding window, causal only): rotation r's KV block sits
    a STATIC r*s_blk positions behind the local q block, so each
    rotation runs the kernel with a static local window of
    ``window - r*s_blk`` — and the ring STOPS after
    ceil(window/s_blk) rotations instead of n-1: windowed
    long-context pays O(W) communication, not O(S)."""
    from ..ops.flash import flash_attention_lse

    s_blk = q.shape[1]

    def block(k_cur, v_cur, src, diag: bool, skip: bool = False,
              win=None):
        if skip:
            o = jnp.zeros(q.shape, jnp.float32)
            lse = jnp.full(q.shape[:2] + q.shape[2:3], BIG_NEG,
                           jnp.float32)
            return o, lse
        kvm = None
        if mask is not None:
            kvm = jax.lax.dynamic_slice_in_dim(mask, src * s_blk, s_blk,
                                               axis=1)
        o, lse = flash_attention_lse(q, k_cur, v_cur, causal=diag,
                                     scale=scale, kv_mask=kvm,
                                     window=win)
        # flash lse is [B, H, Sq] -> ring's [B, Sq, H] accumulator
        # convention.
        return o.astype(jnp.float32), jnp.transpose(lse, (0, 2, 1))

    def combine(acc, o_r, lse_r):
        o, lse_acc = acc
        new_lse = jnp.logaddexp(lse_acc, lse_r)
        w_old = jnp.where(lse_acc > BIG_NEG / 2,
                          jnp.exp(lse_acc - new_lse), 0.0)
        w_new = jnp.where(lse_r > BIG_NEG / 2,
                          jnp.exp(lse_r - new_lse), 0.0)
        o = o * w_old[..., None] + o_r * w_new[..., None]
        return o, jnp.where(new_lse > BIG_NEG / 2, new_lse, BIG_NEG)

    if window is not None:
        # Unrolled: the per-rotation window is static, and rotations
        # beyond the window do not happen at all.
        r_max = min(n - 1, (window + s_blk - 1) // s_blk)
        acc = block(k, v, my_idx, diag=True, win=window)
        k_cur, v_cur = k, v
        for r in range(1, r_max + 1):
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            src = (my_idx - r) % n
            o_r, lse_r = jax.lax.cond(
                my_idx >= r,  # otherwise src wrapped to a FUTURE block
                lambda kc, vc, sx: block(kc, vc, sx, diag=False,
                                         win=window - r * s_blk),
                lambda kc, vc, sx: block(kc, vc, sx, diag=False,
                                         skip=True),
                k_cur, v_cur, src)
            acc = combine(acc, o_r, lse_r)
        o, _ = acc
        return o.astype(q.dtype)

    def attend(acc, k_cur, v_cur, r):
        src = (my_idx - r) % n
        if causal:
            # past -> full attend; diagonal -> causal kernel; future ->
            # no kernel at all (the causal FLOP saving).
            idx = jnp.where(src == my_idx, 1,
                            jnp.where(src < my_idx, 0, 2)).astype(jnp.int32)
            o_r, lse_r = jax.lax.switch(
                idx,
                [lambda kc, vc, s: block(kc, vc, s, diag=False),
                 lambda kc, vc, s: block(kc, vc, s, diag=True),
                 lambda kc, vc, s: block(kc, vc, s, diag=False,
                                         skip=True)],
                k_cur, v_cur, src)
        else:
            o_r, lse_r = block(k_cur, v_cur, src, diag=False)
        return combine(acc, o_r, lse_r)

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:2] + q.shape[2:3], BIG_NEG, jnp.float32)

    def step(carry, r):
        o, lse, k_cur, v_cur = carry
        o, lse = attend((o, lse), k_cur, v_cur, r)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    k_cur, v_cur = k, v
    if n > 1:
        (o, lse, k_cur, v_cur), _ = jax.lax.scan(
            step, (o, lse, k, v), jnp.arange(n - 1))
    o, lse = attend((o, lse), k_cur, v_cur, n - 1)
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    mask: Optional[jax.Array] = None,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    batch_axes=("dp", "fsdp"),
):
    """Ring attention over a mesh axis.

    q/k/v: GLOBAL arrays [B, S, H, D]; S must divide by mesh.shape[axis_name].
    ``mask``: optional boolean broadcastable to [B, H, S, S] (True =
    attend) — padded batches keep sequence parallelism (VERDICT r1 #8).
    Its q dim shards with q when full-size; the kv dim stays full and is
    sliced per rotation.  Returns output with the same sharding as q.

    ``window`` (sliding window >= 1; requires causal): the flash ring
    stops rotating after ceil(window/block) hops — communication is O(W),
    not O(S).
    """
    try:
        from jax import shard_map
    except ImportError:   # older jax: translated spellings
        from ._shard_map_compat import shard_map

    if window is not None:
        if not causal:
            raise ValueError("sliding window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1; got {window}")
    batch = active_batch_axes(mesh, batch_axes)
    spec = P(batch, axis_name, None, None)
    sp = mesh.shape.get(axis_name, 1)
    use_flash = _ring_flash_eligible(q, q.shape[1] // max(sp, 1), mask)
    body = functools.partial(_ring_attention_shard, axis_name=axis_name,
                             causal=causal, scale=scale,
                             axis_size=sp, use_flash=use_flash,
                             window=window)
    if mask is None:
        return shard_map(
            lambda q, k, v: body(q, k, v, None), mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    if mask.ndim != 4:
        raise ValueError(f"mask must be 4-d [B,H,Sq,Sk]; got {mask.shape}")
    if use_flash:
        from ..ops.flash import narrow_kv_mask

        # Key-padding mask: the flash body consumes the narrow [B, S]
        # bool form (kv dim full on every shard; sliced per rotation).
        kvm = narrow_kv_mask(mask, q.shape[0], k.shape[1])
        return shard_map(
            body, mesh=mesh,
            in_specs=(spec, spec, spec, P(batch, None)),
            out_specs=spec,
            check_vma=False,
        )(q, k, v, kvm)
    mask_spec = P(batch if mask.shape[0] > 1 else None,
                  None,
                  axis_name if mask.shape[2] > 1 else None,
                  None)  # kv dim full on every shard; sliced per rotation
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, mask)
