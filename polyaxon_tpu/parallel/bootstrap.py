"""Multi-host bootstrap: env-injected topology -> jax.distributed.

This is the north-star wiring (SURVEY.md 3.2/5.8): the converter/agent
inject the PTPU_* env block (see ``compiler.topology.ProcessTopology
.process_env``); calling ``initialize_from_env()`` before any JAX
computation starts the XLA coordination service in process 0 and connects
every other process — replacing the reference's delegated TF_CONFIG /
NCCL / MPI bootstrap entirely.  Collectives then ride ICI within a slice
and DCN across slices with no further user configuration.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)

ENV_COORDINATOR = "PTPU_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "PTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "PTPU_PROCESS_ID"
ENV_NUM_SLICES = "PTPU_NUM_SLICES"
ENV_SLICE_TYPE = "PTPU_SLICE_TYPE"

_initialized = False


@dataclass
class TopologyEnv:
    coordinator_address: str
    num_processes: int
    process_id: int
    num_slices: int = 1
    slice_type: Optional[str] = None

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def topology_from_env() -> Optional[TopologyEnv]:
    """Parse the injected topology block; None when not a managed
    distributed run."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return None
    try:
        return TopologyEnv(
            coordinator_address=addr,
            num_processes=int(os.environ.get(ENV_NUM_PROCESSES, "1")),
            process_id=int(os.environ.get(ENV_PROCESS_ID, "0")),
            num_slices=int(os.environ.get(ENV_NUM_SLICES, "1") or "1"),
            slice_type=os.environ.get(ENV_SLICE_TYPE) or None,
        )
    except ValueError as e:
        raise RuntimeError(f"Malformed PTPU_* topology env: {e}") from e


def initialize_from_env(timeout_s: Optional[int] = None) -> Optional[TopologyEnv]:
    """Bootstrap jax.distributed from env; idempotent; no-op when the
    topology block is absent or trivial (single process)."""
    global _initialized
    topo = topology_from_env()
    if topo is None or not topo.is_distributed:
        return topo
    if _initialized:
        return topo
    import jax

    kwargs = dict(
        coordinator_address=topo.coordinator_address,
        num_processes=topo.num_processes,
        process_id=topo.process_id,
    )
    if timeout_s is not None:
        kwargs["initialization_timeout"] = timeout_s
    logger.info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, "
        "process_id=%d)", topo.coordinator_address, topo.num_processes,
        topo.process_id,
    )
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return topo
