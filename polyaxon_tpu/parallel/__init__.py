"""JAX distributed runtime: the layer the reference never had.

The reference delegates all distributed compute to NCCL/MPI/gRPC via
Kubeflow operators (SURVEY.md 2.5/5.8).  Here the framework owns the device
mesh natively:

- ``bootstrap``:   jax.distributed.initialize from injected PTPU_* env.
- ``mesh``:        mesh construction (ICI x DCN axes) + sharding helpers.
- ``strategies``:  DP/TP/PP/SP/CP/EP train-step builders on pjit/shard_map.
- ``ring``:        ring attention (ppermute KV rotation) for long context.
- ``ulysses``:     all-to-all head/sequence resharding attention.
- ``collectives``: hierarchical ICI/DCN collective helpers.
"""

from .bootstrap import initialize_from_env, topology_from_env
from .constraints import BATCH, ambient_mesh, constrain, current_mesh
from .health import SliceHealth, check_slice_health
from .collectives import (
    all_gather,
    all_reduce,
    all_reduce_mean,
    all_to_all,
    hierarchical_all_reduce,
    reduce_scatter,
    ring_permute,
)
from .mesh import (
    MeshSpec,
    build_mesh,
    data_sharding,
    local_mesh,
    replicate_sharding,
)
from .moe import moe_layer, top1_dispatch
from .pipeline import pipeline_apply
from .ring import ring_attention
from .strategies import (
    TrainStep,
    infer_param_spec,
    make_batch_sharding,
    make_param_shardings,
    make_train_step,
)
from .ulysses import ulysses_attention
