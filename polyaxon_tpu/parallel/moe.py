"""Expert parallelism: switch-style MoE routing with all-to-all dispatch.

EP capability (SURVEY.md 2.12): experts are sharded over the ``ep`` mesh
axis; tokens route to their top-1 expert with a capacity limit, travel via
``all_to_all`` (ICI), run the expert MLP, and return.  Dense einsum
dispatch/combine keeps everything MXU-shaped (no dynamic gathers — XLA
and the TPU both prefer the one-hot matmul form).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import active_batch_axes


def top1_dispatch(logits: jax.Array, capacity: int):
    """Build dispatch/combine tensors for top-1 (switch) routing.

    logits: [T, E] router scores for T tokens.
    Returns (dispatch [T, E, C] bool-ish f32, combine [T, E, C] f32,
    aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [T, E]
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [T, E], -1 elsewhere
    pos_in_expert = jnp.max(pos, axis=-1)  # [T]
    keep = pos_in_expert < capacity
    gate = gate * keep

    pos_onehot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                                dtype=jnp.float32)  # [T, C]
    dispatch = onehot[:, :, None] * pos_onehot[:, None, :] * keep[:, None, None]
    combine = dispatch * gate[:, None, None]

    # Switch load-balancing loss: E * sum_e(fraction_e * prob_e).
    fraction = onehot.mean(axis=0)
    prob_mean = probs.mean(axis=0)
    aux = e * jnp.sum(fraction * prob_mean)
    return dispatch, combine, aux


def moe_layer(
    x: jax.Array,
    router_w: jax.Array,
    expert_w1: jax.Array,
    expert_w2: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
    activation: Callable = jax.nn.gelu,
    batch_axes=("dp", "fsdp"),
):
    """Expert-parallel switch MoE layer.

    x: GLOBAL [B, S, D]; experts sharded over ``ep``:
    router_w [D, E] replicated, expert_w1 [E, D, F], expert_w2 [E, F, D].
    Returns ([B, S, D], aux_loss).

    Tokens are sharded over ``ep`` along the sequence dim (each rank
    routes 1/ep of the tokens; the capacity limit applies per source
    rank), so per-rank expert FLOPs are 1/ep of dense — the point of EP.
    """
    try:
        from jax import shard_map
    except ImportError:   # older jax: translated spellings
        from ._shard_map_compat import shard_map

    b, s, d = x.shape
    e = expert_w1.shape[0]
    ep = mesh.shape.get(axis_name, 1)
    if e % ep:
        raise ValueError(
            f"num experts {e} must be divisible by ep axis size {ep}")
    if s % ep:
        raise ValueError(
            f"sequence length {s} must be divisible by ep axis size {ep}")

    batch = active_batch_axes(mesh, batch_axes)

    def body(xl, rw, w1, w2):
        tl = xl.shape[0] * xl.shape[1]
        flat = xl.reshape(tl, d)
        el = w1.shape[0]
        capacity = max(1, int(capacity_factor * tl / e))

        logits = flat.astype(jnp.float32) @ rw.astype(jnp.float32)
        dispatch, combine, aux = top1_dispatch(logits, capacity)
        # [T, E, C] x [T, D] -> [E, C, D]
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               flat.astype(jnp.float32))
        # Exchange: each rank keeps its own expert rows from every rank.
        expert_in = expert_in.reshape(ep, el, capacity, d)
        expert_in = jax.lax.all_to_all(expert_in, axis_name, split_axis=0,
                                       concat_axis=0, tiled=True)
        # After the tiled all_to_all the leading axis indexes the SOURCE
        # rank and the expert axis holds only OUR local experts.
        expert_in = expert_in.reshape(ep, el, capacity, d)
        xin = expert_in.transpose(1, 0, 2, 3).reshape(el, ep * capacity, d)
        h = jnp.einsum("ecd,edf->ecf", xin, w1.astype(jnp.float32))
        h = activation(h)
        h = jnp.einsum("ecf,efd->ecd", h, w2.astype(jnp.float32))
        # Route back: inverse transpose + all_to_all.
        h = h.reshape(el, ep, capacity, d).transpose(1, 0, 2, 3)
        h = jax.lax.all_to_all(h, axis_name, split_axis=0, concat_axis=0,
                               tiled=True)
        h = h.reshape(e, capacity, d)
        out = jnp.einsum("tec,ecd->td", combine, h)
        # aux differs per token shard: average over every axis the tokens
        # are sharded on so the returned scalar really is replicated.
        aux = jax.lax.pmean(aux, (axis_name,) + (batch or ()))
        return out.reshape(xl.shape).astype(x.dtype), aux

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(batch, axis_name, None), P(), P(axis_name),
                  P(axis_name)),
        out_specs=(P(batch, axis_name, None), P()),
        check_vma=False,
    )(x, router_w, expert_w1, expert_w2)
