"""Collective helpers: the ICI/DCN communication vocabulary.

The reference's communication backends are NCCL/MPI/gRPC, all delegated
(SURVEY.md 5.8).  Here every collective is an XLA op over mesh axes; these
helpers add the hierarchical multi-slice pattern (reduce-scatter inside the
slice on ICI -> allreduce across slices on DCN -> all-gather on ICI),
which XLA also derives automatically from hybrid meshes — the explicit
forms exist for shard_map code and for benchmarks/tests.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str]]


def all_reduce(x: jax.Array, axis: AxisName) -> jax.Array:
    """Sum over one or more mesh axes (inside shard_map)."""
    return jax.lax.psum(x, axis)


def all_reduce_mean(x: jax.Array, axis: AxisName) -> jax.Array:
    return jax.lax.pmean(x, axis)


def reduce_scatter(x: jax.Array, axis: str, *, scatter_dim: int = 0) -> jax.Array:
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_gather(x: jax.Array, axis: str, *, gather_dim: int = 0) -> jax.Array:
    return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=True)


def all_to_all(x: jax.Array, axis: str, *, split_dim: int,
               concat_dim: int) -> jax.Array:
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ring_permute(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate shards around an axis (nearest-neighbor ICI hops)."""
    n = jax.lax.psum(1, axis)
    perm = [(j, (j + shift) % n) for j in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def hierarchical_all_reduce(
    x: jax.Array,
    ici_axis: str = "fsdp",
    dcn_axis: str = "dp",
    *,
    scatter_dim: int = 0,
) -> jax.Array:
    """Bandwidth-optimal multi-slice allreduce (inside shard_map):

    1. reduce-scatter over the ICI axis (each chip ends with 1/n of the sum)
    2. allreduce the shard over the DCN axis (small traffic crosses DCN)
    3. all-gather back over ICI.

    Equivalent to psum over both axes; the explicit form pins the
    DCN-traffic-minimizing schedule and serves as the reference
    implementation for the benchmark suite.
    """
    shard = reduce_scatter(x, ici_axis, scatter_dim=scatter_dim)
    shard = all_reduce(shard, dcn_axis)
    return all_gather(shard, ici_axis, gather_dim=scatter_dim)
