"""Mesh construction and sharding helpers.

TPU-first design (SURVEY.md §7 step 5): one ``jax.sharding.Mesh`` whose
axes encode the parallelism strategy.  Canonical axis names:

    dp   data parallel (gradient allreduce over ICI/DCN)
    fsdp fully-sharded data parallel (param shard + allgather)
    tp   tensor parallel (matmul partials, allreduce/reducescatter)
    pp   pipeline parallel (collective_permute between stages)
    sp   sequence/context parallel (ring attention / Ulysses all-to-all)
    ep   expert parallel (MoE all-to-all)

On multi-slice hardware the mesh is laid out so the *leading* axis (usually
dp) spans DCN between slices while all other axes stay inside a slice on
ICI — the hierarchical-collective recipe from the scaling playbook.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "ep", "tp")


class MeshError(ValueError):
    pass


@dataclass
class MeshSpec:
    """Declarative mesh: axis name -> size; -1 for 'fill with the rest'."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    num_slices: int = 1

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, int]]) -> "MeshSpec":
        data = dict(data or {})
        known = {k: int(v) for k, v in data.items()
                 if k in AXIS_ORDER or k == "num_slices"}
        unknown = set(data) - set(known)
        if unknown:
            raise MeshError(f"Unknown mesh axes: {sorted(unknown)}")
        return cls(**known)

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill -1 axes so the product equals n_devices."""
        sizes = self.sizes()
        fill_axes = [a for a, s in sizes.items() if s == -1]
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if n_devices % fixed:
            raise MeshError(
                f"Mesh axes {sizes} do not divide device count {n_devices}"
            )
        remaining = n_devices // fixed
        if not fill_axes:
            if fixed != n_devices:
                raise MeshError(
                    f"Mesh axes product {fixed} != device count {n_devices}"
                )
        elif len(fill_axes) == 1:
            sizes[fill_axes[0]] = remaining
        else:
            sizes[fill_axes[0]] = remaining
            for a in fill_axes[1:]:
                sizes[a] = 1
        return sizes


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
):
    """Construct a Mesh from a spec over the given (default: all) devices.

    Uses ``mesh_utils.create_device_mesh`` so the logical axes map onto the
    physical ICI torus with nearest-neighbor contiguity; for multi-slice
    topologies the hybrid helper puts the leading axis across DCN.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    axis_names = tuple(a for a in AXIS_ORDER)
    shape = tuple(sizes[a] for a in axis_names)

    if spec.num_slices > 1:
        per_slice = [s for s in shape]
        dcn = [1] * len(shape)
        # dp axis (index 0) spans slices over DCN.
        if shape[0] % spec.num_slices:
            raise MeshError(
                f"dp axis ({shape[0]}) must be divisible by num_slices "
                f"({spec.num_slices}) for hybrid ICI x DCN meshes"
            )
        per_slice[0] = shape[0] // spec.num_slices
        dcn[0] = spec.num_slices
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
            return Mesh(dev_array, axis_names)
        except (ValueError, AssertionError):
            pass  # CPU/virtual devices: fall through to flat layout

    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def local_mesh(**axis_sizes: int):
    """Convenience: mesh over local devices, e.g. local_mesh(dp=4, tp=2)."""
    return build_mesh(MeshSpec.from_dict(axis_sizes))


def active_batch_axes(mesh, batch_axes: Tuple[str, ...] = ("dp", "fsdp")):
    """The subset of ``batch_axes`` with size > 1 on this mesh (or None).

    Single source of truth for "which axes shard the batch dim" — used by
    data_sharding, the strategy library, and every shard_map spec in
    ring/ulysses/pipeline/moe.
    """
    return tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None


def data_sharding(mesh, *, batch_axes: Tuple[str, ...] = ("dp", "fsdp")):
    """NamedSharding for a [batch, ...] array sharded over the data axes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(active_batch_axes(mesh, batch_axes)))


def replicate_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def logical_axis_rules(spec: Optional[MeshSpec] = None) -> List[Tuple[str, Optional[str]]]:
    """flax-style logical->mesh axis rules for the standard vocabulary."""
    return [
        ("batch", ("dp", "fsdp")),
        ("seq", "sp"),
        ("embed", "fsdp"),
        ("hidden", "tp"),
        ("heads", "tp"),
        ("kv", None),
        ("mlp", "tp"),
        ("vocab", "tp"),
        ("expert", "ep"),
        ("stage", "pp"),
    ]
