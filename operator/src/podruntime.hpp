// Pod runtimes: how reconciled pods actually execute.
//
// The reference's operator creates k8s pods and watches their conditions
// (SURVEY.md 2.14).  Here the runtime is pluggable:
//
//  - LocalProcessRuntime: each pod is a local process tree (init
//    containers sequentially, then the main container), stdout/stderr to
//    a per-pod log file.  This is the cluster-less harness the Python
//    agent's ManifestBackend talks to in tests AND the single-box
//    deployment path.
//  - KubePodRuntime (kube.hpp): the api-server transport — POST /pods,
//    poll phases, DELETE on teardown (VERDICT r1 #7).

#pragma once

#include <fcntl.h>
#include <signal.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <map>
#include <string>
#include <utility>
#include <vector>

#include "json.hpp"

namespace ptpu {

enum class PodPhase { Pending, Running, Succeeded, Failed };

inline const char* phase_name(PodPhase p) {
  switch (p) {
    case PodPhase::Pending: return "Pending";
    case PodPhase::Running: return "Running";
    case PodPhase::Succeeded: return "Succeeded";
    case PodPhase::Failed: return "Failed";
  }
  return "Unknown";
}

struct ContainerSpec {
  std::vector<std::string> argv;
  std::vector<std::pair<std::string, std::string>> env;
  std::string workdir;
};

struct PodSpec {
  std::string name;
  std::vector<ContainerSpec> init_containers;
  ContainerSpec main;
  std::string log_path;
  // Cluster runtimes re-emit the converter's pod template as a real Pod
  // object instead of exec'ing parsed argv; the local runtime ignores
  // these.
  Json raw_template;  // the CR's pod template .spec
  std::vector<std::pair<std::string, std::string>> extra_env;
  Json labels;        // owning Operation's labels (selector parity)
  Json annotations;   // pod template metadata.annotations, passed through
  std::string ns = "default";
};

class PodRuntime {
 public:
  virtual ~PodRuntime() = default;
  virtual int launch(const PodSpec& spec) = 0;
  // Re-attach to a pod that already exists (operator restart over a
  // Running operation).  Local processes cannot be re-attached — the
  // restarted operator has no pids — so the default relaunches.
  virtual int adopt(const PodSpec& spec) { return launch(spec); }
  virtual PodPhase poll(int pod_id) = 0;
  virtual int exit_code(int pod_id) = 0;
  // Non-blocking SIGTERM: starts the grace clock so several pods can
  // drain concurrently (gang teardown sends this to every pod first).
  virtual void terminate_pod(int pod_id) {(void)pod_id;}
  virtual void kill_pod(int pod_id) = 0;
  virtual void remove(int pod_id) = 0;
};

class LocalProcessRuntime : public PodRuntime {
 public:
  // grace_ms: time between SIGTERM and SIGKILL.  The framework's
  // preemption design (checkpoint.install_preemption_hook) relies on the
  // trainer seeing SIGTERM and flushing a final checkpoint — an immediate
  // SIGKILL would defeat it (ADVICE r1).  Equivalent of k8s
  // terminationGracePeriodSeconds.
  explicit LocalProcessRuntime(int grace_ms = 10000) : grace_ms_(grace_ms) {}

  int launch(const PodSpec& spec) override {
    int id = next_id_++;
    Pod pod;
    pod.spec = spec;
    pod.stage = 0;
    pod.phase = PodPhase::Pending;
    pods_[id] = std::move(pod);
    advance(pods_[id]);
    return id;
  }

  PodPhase poll(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return PodPhase::Failed;
    Pod& pod = it->second;
    if (pod.phase == PodPhase::Succeeded || pod.phase == PodPhase::Failed)
      return pod.phase;
    if (pod.pid > 0) {
      int status = 0;
      pid_t r = waitpid(pod.pid, &status, WNOHANG);
      if (r == pod.pid) {
        int code = WIFEXITED(status) ? WEXITSTATUS(status)
                                     : 128 + WTERMSIG(status);
        pod.pid = -1;
        if (code != 0) {
          pod.exit_code = code;
          pod.phase = PodPhase::Failed;
        } else if (pod.stage <
                   static_cast<int>(pod.spec.init_containers.size())) {
          pod.stage++;
          advance(pod);  // next init container or main
        } else {
          pod.exit_code = 0;
          pod.phase = PodPhase::Succeeded;
        }
      }
    }
    return pod.phase;
  }

  int exit_code(int pod_id) override {
    auto it = pods_.find(pod_id);
    return it == pods_.end() ? -1 : it->second.exit_code;
  }

  void terminate_pod(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return;
    Pod& pod = it->second;
    if (pod.pid > 0 && !pod.term_sent) {
      // Each pod is its own process group (setpgid in spawn): signal the
      // whole group.  SIGTERM starts the grace clock so a preemption
      // hook can flush its checkpoint before kill_pod escalates.
      ::kill(-pod.pid, SIGTERM);
      pod.term_sent = true;
      pod.term_monotonic_ms = now_ms();
    }
  }

  void kill_pod(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return;
    Pod& pod = it->second;
    if (pod.pid > 0) {
      terminate_pod(pod_id);
      // Wait out whatever remains of the grace period (50ms polls),
      // then SIGKILL the whole GROUP unconditionally — even if the
      // leader already exited, descendants that ignored SIGTERM must
      // not survive as orphans holding the TPU.
      int status = 0;
      bool reaped = false;
      while (true) {
        pid_t r = waitpid(pod.pid, &status, WNOHANG);
        if (r == pod.pid) {
          reaped = true;
          break;
        }
        if (now_ms() - pod.term_monotonic_ms >= grace_ms_) break;
        usleep(50 * 1000);
      }
      ::kill(-pod.pid, SIGKILL);
      if (!reaped) waitpid(pod.pid, &status, 0);
      pod.pid = -1;
    }
    pod.exit_code = 137;
    pod.phase = PodPhase::Failed;
  }

  void remove(int pod_id) override { pods_.erase(pod_id); }

 private:
  struct Pod {
    PodSpec spec;
    int stage = 0;  // index into init containers; == size() -> main
    pid_t pid = -1;
    int exit_code = -1;
    PodPhase phase = PodPhase::Pending;
    bool term_sent = false;
    long long term_monotonic_ms = 0;
  };

  static long long now_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<long long>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  }

  void advance(Pod& pod) {
    const ContainerSpec& c =
        pod.stage < static_cast<int>(pod.spec.init_containers.size())
            ? pod.spec.init_containers[pod.stage]
            : pod.spec.main;
    pod.pid = spawn(c, pod.spec.log_path);
    if (pod.pid < 0) {
      pod.exit_code = 127;
      pod.phase = PodPhase::Failed;
    } else {
      pod.phase = PodPhase::Running;
    }
  }

  static pid_t spawn(const ContainerSpec& c, const std::string& log_path) {
    if (c.argv.empty()) return -1;
    pid_t pid = fork();
    if (pid > 0) {
      // Set the group from BOTH sides (races with the child's own
      // setpgid); whichever runs first wins, and a group-signal sent
      // right after launch can never hit the operator's group.
      setpgid(pid, pid);
      return pid;
    }
    if (pid < 0) return pid;

    // child: lead a fresh process group so kill_pod can signal the tree
    setpgid(0, 0);
    if (!log_path.empty()) {
      int fd = open(log_path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
      if (fd >= 0) {
        dup2(fd, STDOUT_FILENO);
        dup2(fd, STDERR_FILENO);
        close(fd);
      }
    }
    if (!c.workdir.empty() && chdir(c.workdir.c_str()) != 0) _exit(127);
    for (const auto& kv : c.env)
      setenv(kv.first.c_str(), kv.second.c_str(), 1);
    std::vector<char*> argv;
    argv.reserve(c.argv.size() + 1);
    for (const auto& a : c.argv)
      argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    execvp(argv[0], argv.data());
    _exit(127);
  }

  int grace_ms_ = 10000;
  int next_id_ = 1;
  std::map<int, Pod> pods_;
};

}  // namespace ptpu
