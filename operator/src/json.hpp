// Minimal JSON value: parse + serialize, order-preserving objects.
//
// The operator's wire format is the Operation CR JSON the agent writes
// (polyaxon_tpu/runner/agent.py ManifestBackend).  Order preservation
// matters: replicaSpecs insertion order defines process-id offsets, the
// same contract as compiler.topology.ProcessTopology.
//
// No external deps (header-only, C++17).

#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace ptpu {

class Json;
using JsonMember = std::pair<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  explicit Json(bool b) : type_(Type::Bool), bool_(b) {}
  explicit Json(double n) : type_(Type::Number), num_(n) {}
  explicit Json(int n) : type_(Type::Number), num_(n) {}
  explicit Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  explicit Json(const char* s) : type_(Type::String), str_(s) {}

  static Json array() { Json j; j.type_ = Type::Array; return j; }
  static Json object() { Json j; j.type_ = Type::Object; return j; }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  long as_int(long dflt = 0) const {
    return type_ == Type::Number ? static_cast<long>(num_) : dflt;
  }
  const std::string& as_string() const { return str_; }

  const std::vector<Json>& items() const { return arr_; }
  std::vector<Json>& items() { return arr_; }
  const std::vector<JsonMember>& members() const { return obj_; }

  // Object access; returns null singleton for missing keys.
  const Json& operator[](const std::string& key) const {
    static const Json null_json;
    for (const auto& kv : obj_)
      if (kv.first == key) return kv.second;
    return null_json;
  }
  bool contains(const std::string& key) const {
    for (const auto& kv : obj_)
      if (kv.first == key) return true;
    return false;
  }
  void set(const std::string& key, Json value) {
    for (auto& kv : obj_)
      if (kv.first == key) { kv.second = std::move(value); return; }
    obj_.emplace_back(key, std::move(value));
  }
  void push_back(Json value) { arr_.push_back(std::move(value)); }

  // ---- parsing ----------------------------------------------------------

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json out = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size())
      throw std::runtime_error("trailing characters at " +
                               std::to_string(pos));
    return out;
  }

  // ---- serialization ----------------------------------------------------

  std::string dump(int indent = 0, int depth = 0) const {
    std::ostringstream os;
    write(os, indent, depth);
    return os.str();
  }

 private:
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::vector<Json> arr_;
  std::vector<JsonMember> obj_;

  static void skip_ws(const std::string& s, size_t& pos) {
    while (pos < s.size() &&
           (s[pos] == ' ' || s[pos] == '\t' || s[pos] == '\n' ||
            s[pos] == '\r'))
      ++pos;
  }

  static void expect(const std::string& s, size_t& pos, char c) {
    if (pos >= s.size() || s[pos] != c)
      throw std::runtime_error(std::string("expected '") + c + "' at " +
                               std::to_string(pos));
    ++pos;
  }

  static Json parse_value(const std::string& s, size_t& pos) {
    skip_ws(s, pos);
    if (pos >= s.size()) throw std::runtime_error("unexpected end");
    char c = s[pos];
    if (c == '{') return parse_object(s, pos);
    if (c == '[') return parse_array(s, pos);
    if (c == '"') return Json(parse_string(s, pos));
    if (c == 't' || c == 'f') return parse_bool(s, pos);
    if (c == 'n') { parse_literal(s, pos, "null"); return Json(); }
    return parse_number(s, pos);
  }

  static void parse_literal(const std::string& s, size_t& pos,
                            const char* lit) {
    for (const char* p = lit; *p; ++p, ++pos)
      if (pos >= s.size() || s[pos] != *p)
        throw std::runtime_error("bad literal at " + std::to_string(pos));
  }

  static Json parse_bool(const std::string& s, size_t& pos) {
    if (s[pos] == 't') { parse_literal(s, pos, "true"); return Json(true); }
    parse_literal(s, pos, "false");
    return Json(false);
  }

  static Json parse_number(const std::string& s, size_t& pos) {
    size_t start = pos;
    if (pos < s.size() && (s[pos] == '-' || s[pos] == '+')) ++pos;
    while (pos < s.size() &&
           (std::isdigit(static_cast<unsigned char>(s[pos])) ||
            s[pos] == '.' || s[pos] == 'e' || s[pos] == 'E' ||
            s[pos] == '-' || s[pos] == '+'))
      ++pos;
    if (pos == start) throw std::runtime_error("bad number");
    return Json(std::stod(s.substr(start, pos - start)));
  }

  static std::string parse_string(const std::string& s, size_t& pos) {
    expect(s, pos, '"');
    std::string out;
    while (pos < s.size() && s[pos] != '"') {
      char c = s[pos++];
      if (c == '\\') {
        if (pos >= s.size()) throw std::runtime_error("bad escape");
        char e = s[pos++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos + 4 > s.size()) throw std::runtime_error("bad \\u");
            unsigned cp = std::stoul(s.substr(pos, 4), nullptr, 16);
            pos += 4;
            // UTF-8 encode (BMP only; surrogate pairs folded naively).
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            throw std::runtime_error("bad escape char");
        }
      } else {
        out += c;
      }
    }
    expect(s, pos, '"');
    return out;
  }

  static Json parse_array(const std::string& s, size_t& pos) {
    expect(s, pos, '[');
    Json out = array();
    skip_ws(s, pos);
    if (pos < s.size() && s[pos] == ']') { ++pos; return out; }
    while (true) {
      out.arr_.push_back(parse_value(s, pos));
      skip_ws(s, pos);
      if (pos < s.size() && s[pos] == ',') { ++pos; continue; }
      expect(s, pos, ']');
      return out;
    }
  }

  static Json parse_object(const std::string& s, size_t& pos) {
    expect(s, pos, '{');
    Json out = object();
    skip_ws(s, pos);
    if (pos < s.size() && s[pos] == '}') { ++pos; return out; }
    while (true) {
      skip_ws(s, pos);
      std::string key = parse_string(s, pos);
      skip_ws(s, pos);
      expect(s, pos, ':');
      out.obj_.emplace_back(key, parse_value(s, pos));
      skip_ws(s, pos);
      if (pos < s.size() && s[pos] == ',') { ++pos; continue; }
      expect(s, pos, '}');
      return out;
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof buf, "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  void write(std::ostringstream& os, int indent, int depth) const {
    const std::string pad(indent * (depth + 1), ' ');
    const std::string end_pad(indent * depth, ' ');
    const char* nl = indent ? "\n" : "";
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 1e15) {
          os << static_cast<long long>(num_);
        } else {
          os << num_;
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[' << nl;
        for (size_t i = 0; i < arr_.size(); ++i) {
          os << pad;
          arr_[i].write(os, indent, depth + 1);
          if (i + 1 < arr_.size()) os << ',';
          os << nl;
        }
        os << end_pad << ']';
        break;
      }
      case Type::Object: {
        os << '{' << nl;
        for (size_t i = 0; i < obj_.size(); ++i) {
          os << pad;
          write_string(os, obj_[i].first);
          os << (indent ? ": " : ":");
          obj_[i].second.write(os, indent, depth + 1);
          if (i + 1 < obj_.size()) os << ',';
          os << nl;
        }
        os << end_pad << '}';
        break;
      }
    }
  }
};

}  // namespace ptpu
