// The operator's reconciliation loop (native component).
//
// Parity target: the reference's Go operator (SURVEY.md 2.14) — watch
// Operation CRs, create replica pods with stable identities, aggregate
// pod conditions into a run phase, enforce restart/backoff/deadline/stop
// semantics, and report status.  The CR transport is pluggable:
//
//   FileCRStore  — the agent's ManifestBackend file protocol:
//     <cluster>/operations/<name>.json   CR (+"services")
//     <cluster>/status/<name>.json       reconciled status (we write)
//     <cluster>/logs/<name>/<pod>.log    pod logs
//   KubeCRStore  — kube.hpp: list CRs from a kube-apiserver, PATCH the
//     /status subresource back (VERDICT r1 #7).
//
// TPU-specific semantics vs the reference: a distributed Operation is a
// gang — TPU slices cannot run partially, so ANY replica failure fails
// the whole attempt, all pods are torn down, and the attempt restarts
// from the checkpoint (backoffLimit attempts).  Per-pod process ids are
// stamped here (PTPU_PROCESS_ID / PTPU_REPLICA_INDEX), completing the
// role-level env the converter emits.

#pragma once

#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <dirent.h>
#include <fstream>
#include <map>
#include <memory>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "json.hpp"
#include "podruntime.hpp"

namespace ptpu {

inline double now_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

inline bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path);
  if (!f) return false;
  std::ostringstream ss;
  ss << f.rdbuf();
  *out = ss.str();
  return true;
}

inline void write_file_atomic(const std::string& path,
                              const std::string& content) {
  std::string tmp = path + ".tmp";
  {
    std::ofstream f(tmp);
    f << content;
  }
  std::rename(tmp.c_str(), path.c_str());
}

inline int free_port() {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 0;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
    close(fd);
    return 0;
  }
  socklen_t len = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  int port = ntohs(addr.sin_port);
  close(fd);
  return port;
}

// -- CR transport ----------------------------------------------------------

enum class CRRead { NotFound, Unchanged, Updated, ParseError };

class CRStore {
 public:
  virtual ~CRStore() = default;
  // Refresh + enumerate current CR names (one call per tick).
  virtual std::vector<std::string> list() = 0;
  // Read one CR.  `known_generation` is the last generation reconciled;
  // Unchanged means the caller can skip re-parsing.
  virtual CRRead read(const std::string& name, long known_generation,
                      Json* cr, long* generation, std::string* error) = 0;
  virtual void write_status(const std::string& name,
                            const Json& status) = 0;
  virtual void clear_status(const std::string& name) = 0;
  // Previously-published status for a CR this process has not yet
  // reconciled (operator restart): lets the reconciler adopt terminal
  // operations instead of re-launching them.
  virtual Json prior_status(const std::string& name) {
    (void)name;
    return Json();
  }
  // Directory for pod logs; empty when the runtime owns logging (kube).
  virtual std::string log_dir(const std::string& op_name) = 0;
  // Local transports run every pod on this host (loopback coordinator,
  // loopback endpoints); cluster transports rely on the converter's DNS.
  virtual bool local_network() const { return true; }
};

class FileCRStore : public CRStore {
 public:
  explicit FileCRStore(std::string cluster_dir)
      : dir_(std::move(cluster_dir)) {
    mkdir((dir_ + "/operations").c_str(), 0755);
    mkdir((dir_ + "/status").c_str(), 0755);
    mkdir((dir_ + "/logs").c_str(), 0755);
  }

  std::vector<std::string> list() override {
    std::vector<std::string> names;
    DIR* d = opendir((dir_ + "/operations").c_str());
    if (!d) return names;
    while (dirent* e = readdir(d)) {
      std::string fname = e->d_name;
      if (fname.size() < 6 || fname.substr(fname.size() - 5) != ".json")
        continue;
      names.push_back(fname.substr(0, fname.size() - 5));
    }
    closedir(d);
    return names;
  }

  CRRead read(const std::string& name, long known_generation, Json* cr,
              long* generation, std::string* error) override {
    std::string path = dir_ + "/operations/" + name + ".json";
    struct stat st{};
    if (stat(path.c_str(), &st) != 0) return CRRead::NotFound;
    // Nanosecond mtime: second-granularity misses rapid CR patches.
    *generation = static_cast<long>(st.st_mtim.tv_sec) * 1000000000L +
                  st.st_mtim.tv_nsec;
    if (*generation == known_generation) return CRRead::Unchanged;
    std::string text;
    if (!read_file(path, &text)) return CRRead::NotFound;
    try {
      Json doc = Json::parse(text);
      *cr = doc.contains("operation") ? doc["operation"] : doc;
      return CRRead::Updated;
    } catch (const std::exception& e) {
      *error = e.what();
      return CRRead::ParseError;
    }
  }

  void write_status(const std::string& name, const Json& status) override {
    write_file_atomic(dir_ + "/status/" + name + ".json", status.dump(1));
  }

  void clear_status(const std::string& name) override {
    std::remove((dir_ + "/status/" + name + ".json").c_str());
  }

  Json prior_status(const std::string& name) override {
    std::string text;
    if (!read_file(dir_ + "/status/" + name + ".json", &text))
      return Json();
    try {
      return Json::parse(text);
    } catch (const std::exception&) {
      return Json();  // truncated/partial write: treat as absent
    }
  }

  std::string log_dir(const std::string& op_name) override {
    std::string dir = dir_ + "/logs/" + op_name;
    mkdir(dir.c_str(), 0755);
    return dir;
  }

 private:
  std::string dir_;
};

// -- reconciler ------------------------------------------------------------

struct ReplicaState {
  std::string pod_name;
  int pod_id = -1;
  int restarts = 0;
  PodPhase phase = PodPhase::Pending;
  int exit_code = -1;
};

struct OperationState {
  Json cr;
  std::string name;
  long generation = 0;  // change-detection token: file mtime ns / kube
                        // metadata.generation — NOT published
  long observed_generation = 0;  // published in status: the CR's real
                                 // metadata.generation, or a per-op
                                 // update counter when the CR has none
                                 // (file store)
  double started_at = 0;
  double finished_at = 0;
  int attempt = 0;  // gang restart attempts (distributed) / pod restarts
  std::string phase = "Pending";
  std::string message;
  std::vector<ReplicaState> replicas;
  int coordinator_port = 0;
};

class Reconciler {
 public:
  Reconciler(std::string cluster_dir, PodRuntime* runtime)
      : owned_store_(new FileCRStore(std::move(cluster_dir))),
        store_(owned_store_.get()),
        runtime_(runtime) {}

  Reconciler(CRStore* store, PodRuntime* runtime)
      : store_(store), runtime_(runtime) {}

  // The generation to PUBLISH as status.observedGeneration: the CR's
  // own metadata.generation when the apiserver maintains one; for
  // file-store CRs (no apiserver) a small per-op update counter.  The
  // raw change-detection token (nanosecond mtime) must never leak into
  // status — a drift check comparing it to metadata.generation would
  // silently never match (VERDICT r3 weak #7).
  static long observed_generation_of(const Json& cr, long fallback) {
    if (cr.contains("metadata") && cr["metadata"].contains("generation"))
      return cr["metadata"]["generation"].as_int(fallback);
    return fallback;
  }

  // One reconcile pass over every CR; returns number of live operations.
  int tick() {
    std::set<std::string> seen;
    for (const std::string& name : store_->list()) {
      seen.insert(name);
      reconcile_one(name);
    }
    // CR deleted -> tear down and clear status.
    for (auto it = ops_.begin(); it != ops_.end();) {
      if (!seen.count(it->first)) {
        teardown(it->second);
        store_->clear_status(it->first);
        it = ops_.erase(it);
      } else {
        ++it;
      }
    }
    int live = 0;
    for (auto& kv : ops_)
      if (kv.second.phase == "Running" || kv.second.phase == "Pending")
        ++live;
    return live;
  }

  const OperationState* get(const std::string& name) const {
    auto it = ops_.find(name);
    return it == ops_.end() ? nullptr : &it->second;
  }

 private:
  std::unique_ptr<CRStore> owned_store_;
  CRStore* store_;
  PodRuntime* runtime_;
  std::map<std::string, OperationState> ops_;

  void reconcile_one(const std::string& name) {
    auto it = ops_.find(name);
    long known = it == ops_.end() ? -1 : it->second.generation;
    Json cr;
    long generation = 0;
    std::string error;
    switch (store_->read(name, known, &cr, &generation, &error)) {
      case CRRead::NotFound:
        return;  // deletion is handled by tick()'s sweep
      case CRRead::ParseError:
        // Partially-written file (writer not atomic): retry next tick,
        // but a CR that never parses must surface, not hang.
        if (it == ops_.end()) {
          OperationState bad;
          bad.name = name;
          bad.generation = generation;
          bad.phase = "Failed";
          bad.message = "invalid CR: " + error;
          ops_[name] = bad;
          publish(ops_[name]);
        }
        return;
      case CRRead::Unchanged:
        break;
      case CRRead::Updated:
        if (it == ops_.end()) {
          OperationState op;
          op.cr = cr;
          op.name = name;
          op.generation = generation;
          op.started_at = now_s();
          // Operator restart: a CR we have never reconciled may carry a
          // published status.  Terminal operations are adopted as-is —
          // relaunching a Failed/Succeeded/Stopped run on every operator
          // restart would silently re-run finished jobs.  Non-terminal
          // prior status restores the attempt counter so backoff
          // accounting survives the restart.
          Json prior = store_->prior_status(name);
          const std::string& prior_phase = prior["phase"].as_string();
          op.attempt = static_cast<int>(prior["attempt"].as_int(0));
          // File-store CRs have no metadata.generation: the fallback
          // counter must resume from the last PUBLISHED value, not
          // reset to 1 — a client that saw "observed at generation 4"
          // must never watch the status regress below it.
          long prior_og = prior["observedGeneration"].as_int(0);
          op.observed_generation =
              observed_generation_of(cr, prior_og > 0 ? prior_og : 1);
          if (prior_phase == "Succeeded" || prior_phase == "Failed" ||
              prior_phase == "Stopped") {
            op.phase = prior_phase;
            op.message = prior["message"].as_string();
            op.finished_at = prior["finishedAt"].as_number(now_s());
            ops_[name] = op;
            return;
          }
          if (prior_phase == "Running" && !store_->local_network() &&
              adopt_running(op, prior)) {
            // Cluster pods survive an operator restart: re-attach to
            // the live gang instead of deleting + recreating it (a
            // restarted operator must not reset healthy long trainings
            // to their last checkpoint).  Local processes cannot be
            // re-attached (no pids), so file mode relaunches below.
            ops_[name] = op;
            break;  // supervise() polls the adopted pods
          }
          ops_[name] = op;
          launch(ops_[name]);
        } else {
          // Spec update: only `stopped` is acted on mid-flight (parity:
          // reference stops via CR patch); other edits take effect on
          // the next attempt.
          OperationState& op = it->second;
          bool was_invalid = op.phase == "Failed" &&
                             op.message.rfind("invalid CR", 0) == 0 &&
                             op.replicas.empty();
          op.cr = cr;
          op.generation = generation;
          long prev_observed = op.observed_generation;
          op.observed_generation =
              observed_generation_of(cr, op.observed_generation + 1);
          // Publish the newly-observed generation even when the spec
          // edit changes nothing else mid-flight (edits other than
          // `stopped` take effect on the next attempt): drift checks
          // compare status.observedGeneration to metadata.generation.
          if (op.observed_generation != prev_observed) publish(op);
          if (was_invalid) {
            // A CR that failed to parse has been rewritten with valid
            // JSON (non-atomic writer finished): recover instead of
            // staying Failed forever.
            op.phase = "Pending";
            op.message.clear();
            op.started_at = now_s();
            op.attempt = 0;
            launch(op);
          }
        }
        break;
    }
    supervise(ops_[name]);
  }

  // -- pod construction --------------------------------------------------

  static ContainerSpec container_from(const Json& c) {
    ContainerSpec out;
    for (const auto& a : c["command"].items())
      out.argv.push_back(a.as_string());
    for (const auto& a : c["args"].items())
      out.argv.push_back(a.as_string());
    for (const auto& e : c["env"].items()) {
      if (e.contains("value") && e["value"].is_string())
        out.env.emplace_back(e["name"].as_string(),
                             e["value"].as_string());
    }
    if (c["workingDir"].is_string()) out.workdir = c["workingDir"].as_string();
    return out;
  }

  static const Json& main_container(const Json& pod_spec) {
    static const Json null_json;
    for (const auto& c : pod_spec["containers"].items())
      if (c["name"].as_string() == "ptpu-main") return c;
    // Fall back to the first container (hand-written CRs).
    const auto& cs = pod_spec["containers"].items();
    return cs.empty() ? null_json : cs.front();
  }

  // `tmpl` is the CR's pod template ({"metadata": ..., "spec": ...}) or
  // a bare pod spec (hand-written CRs).
  PodSpec build_pod(const OperationState& op, const Json& tmpl,
                    const std::string& pod_name,
                    const std::vector<std::pair<std::string, std::string>>&
                        extra_env) {
    const Json& pod_spec = tmpl.contains("spec") ? tmpl["spec"] : tmpl;
    PodSpec pod;
    pod.name = pod_name;
    std::string log_dir = store_->log_dir(op.name);
    if (!log_dir.empty()) pod.log_path = log_dir + "/" + pod_name + ".log";
    for (const auto& ic : pod_spec["initContainers"].items())
      pod.init_containers.push_back(container_from(ic));
    pod.main = container_from(main_container(pod_spec));
    for (const auto& kv : extra_env) {
      bool replaced = false;
      for (auto& existing : pod.main.env)
        if (existing.first == kv.first) {
          existing.second = kv.second;
          replaced = true;
        }
      if (!replaced) pod.main.env.push_back(kv);
    }
    // Cluster runtimes re-emit the template as a real Pod object.
    pod.raw_template = pod_spec;
    pod.extra_env = extra_env;
    pod.labels = op.cr["metadata"]["labels"];
    pod.annotations = tmpl["metadata"]["annotations"];
    pod.ns = op.cr["metadata"]["namespace"].is_string()
                 ? op.cr["metadata"]["namespace"].as_string()
                 : "default";
    return pod;
  }

  void launch(OperationState& op) {
    const Json& spec = op.cr["spec"];
    op.replicas.clear();
    op.phase = "Running";
    op.message = "attempt " + std::to_string(op.attempt + 1);

    if (spec.contains("replicaSpecs")) {
      // Distributed gang: process ids follow replicaSpecs order — the
      // same contract as compiler.topology (coordinator group first).
      bool local = store_->local_network();
      std::string coord;
      if (local) {
        // All pods share this host: rewrite the converter's DNS
        // coordinator to a loopback port.
        if (op.coordinator_port == 0) op.coordinator_port = free_port();
        coord = "127.0.0.1:" + std::to_string(op.coordinator_port);
      }
      int process_id = 0;
      for (const auto& role_kv : spec["replicaSpecs"].members()) {
        const std::string& role = role_kv.first;
        const Json& rs = role_kv.second;
        long n = rs["replicas"].as_int(1);
        const Json& tmpl = rs["template"];
        for (long i = 0; i < n; ++i, ++process_id) {
          std::string run = run_uuid(op);
          std::string pod_name =
              run + "-" + role + "-" + std::to_string(i);
          std::vector<std::pair<std::string, std::string>> extra = {
              {"PTPU_PROCESS_ID", std::to_string(process_id)},
              {"PTPU_REPLICA_INDEX", std::to_string(i)},
              {"PTPU_REPLICA_ROLE", role},
              {"POLYAXON_TPU_POD_ID", pod_name},
          };
          if (local)
            extra.emplace_back("PTPU_COORDINATOR_ADDRESS", coord);
          ReplicaState rep;
          rep.pod_name = pod_name;
          rep.restarts = op.attempt;  // gang: every attempt restarts all
          rep.pod_id = runtime_->launch(
              build_pod(op, tmpl, pod_name, extra));
          op.replicas.push_back(rep);
        }
      }
    } else {
      long n = spec.contains("replicas") ? spec["replicas"].as_int(1) : 1;
      const Json& tmpl = spec["template"];
      for (long i = 0; i < n; ++i) {
        std::string pod_name = run_uuid(op) + "-main-" +
                               std::to_string(i);
        ReplicaState rep;
        rep.pod_name = pod_name;
        rep.restarts = op.attempt;
        rep.pod_id = runtime_->launch(build_pod(
            op, tmpl, pod_name,
            {{"POLYAXON_TPU_POD_ID", pod_name}}));
        op.replicas.push_back(rep);
      }
    }
    publish(op);
  }

  // Re-attach to the pods a previous operator instance launched, using
  // the replica names it published.  Returns false when the prior
  // status carries no replicas (nothing to adopt -> caller relaunches).
  bool adopt_running(OperationState& op, const Json& prior) {
    const Json& reps = prior["replicaStatuses"];
    if (!reps.is_object() || reps.members().empty()) return false;
    op.phase = "Running";
    op.message = prior["message"].as_string();
    std::string ns = op.cr["metadata"]["namespace"].is_string()
                         ? op.cr["metadata"]["namespace"].as_string()
                         : "default";
    for (const auto& kv : reps.members()) {
      PodSpec spec;
      spec.name = kv.first;
      spec.ns = ns;
      ReplicaState rep;
      rep.pod_name = kv.first;
      rep.restarts = op.attempt;
      rep.pod_id = runtime_->adopt(spec);
      op.replicas.push_back(rep);
    }
    return true;
  }

  static std::string run_uuid(const OperationState& op) {
    const Json& labels = op.cr["metadata"]["labels"];
    if (labels.contains("polyaxon-tpu/run-uuid"))
      return labels["polyaxon-tpu/run-uuid"].as_string();
    return op.name;
  }

  // -- supervision -------------------------------------------------------

  void supervise(OperationState& op) {
    if (op.phase == "Succeeded" || op.phase == "Failed" ||
        op.phase == "Stopped")
      return;
    const Json& spec = op.cr["spec"];

    if (spec["stopped"].as_bool(false)) {
      teardown(op);
      op.phase = "Stopped";
      op.message = "stop requested";
      op.finished_at = now_s();
      publish(op);
      return;
    }

    long deadline = spec["activeDeadlineSeconds"].as_int(0);
    if (deadline > 0 && now_s() - op.started_at > deadline) {
      teardown(op);
      op.phase = "Failed";
      op.message = "activeDeadlineSeconds exceeded";
      op.finished_at = now_s();
      publish(op);
      return;
    }

    bool changed = false;
    int succeeded = 0, failed = 0;
    for (auto& rep : op.replicas) {
      PodPhase before = rep.phase;
      rep.phase = runtime_->poll(rep.pod_id);
      rep.exit_code = runtime_->exit_code(rep.pod_id);
      if (rep.phase != before) changed = true;
      if (rep.phase == PodPhase::Succeeded) ++succeeded;
      if (rep.phase == PodPhase::Failed) ++failed;
    }

    bool gang = spec.contains("replicaSpecs");
    long backoff = spec["backoffLimit"].as_int(0);

    if (failed > 0) {
      // TPU gang semantics: any replica failure fails the attempt.
      teardown(op);
      if (op.attempt < backoff) {
        op.attempt++;
        launch(op);  // publishes "attempt N"
        return;
      }
      op.phase = "Failed";
      op.message = gang ? "replica failure (gang torn down)"
                        : "pod failed";
      op.finished_at = now_s();
      publish(op);
      return;
    }
    if (succeeded == static_cast<int>(op.replicas.size()) &&
        !op.replicas.empty()) {
      op.phase = "Succeeded";
      op.finished_at = now_s();
      publish(op);
      return;
    }
    if (changed) publish(op);
  }

  void teardown(OperationState& op) {
    // SIGTERM every pod first so their grace periods overlap — the gang
    // drains in ~one grace window instead of replicas × grace.
    for (auto& rep : op.replicas) {
      if (rep.pod_id >= 0 &&
          runtime_->poll(rep.pod_id) == PodPhase::Running)
        runtime_->terminate_pod(rep.pod_id);
    }
    for (auto& rep : op.replicas) {
      if (rep.pod_id >= 0) {
        if (runtime_->poll(rep.pod_id) == PodPhase::Running)
          runtime_->kill_pod(rep.pod_id);
        runtime_->remove(rep.pod_id);
        rep.pod_id = -1;
      }
    }
  }

  // Endpoint host: loopback for local runtimes; the CR's declared host
  // (annotation, set by the converter from service DNS) in-cluster.
  std::string endpoint_host(const OperationState& op) const {
    const Json& ann = op.cr["metadata"]["annotations"];
    if (ann.contains("polyaxon-tpu/endpoint-host"))
      return ann["polyaxon-tpu/endpoint-host"].as_string();
    if (store_->local_network()) return "127.0.0.1";
    std::string ns = op.cr["metadata"]["namespace"].is_string()
                         ? op.cr["metadata"]["namespace"].as_string()
                         : "default";
    // Distributed gangs get the agent-created headless service
    // "<name>-hs"; service kinds get the ClusterIP Service "<name>"
    // the agent creates for CRs with spec.ports.
    if (op.cr["spec"].contains("replicaSpecs"))
      return op.name + "-hs." + ns;
    return op.name + "." + ns;
  }

  void publish(const OperationState& op) {
    Json status = Json::object();
    status.set("phase", Json(op.phase));
    status.set("message", Json(op.message));
    status.set("attempt", Json(op.attempt));
    // Service kinds: advertise reachable endpoints.
    const Json& ports = op.cr["spec"]["ports"];
    if (ports.is_array() && !ports.items().empty()) {
      Json endpoints = Json::array();
      std::string host = endpoint_host(op);
      for (const auto& p : ports.items())
        endpoints.push_back(
            Json(host + ":" + std::to_string(p.as_int())));
      status.set("endpoints", endpoints);
    }
    status.set("observedGeneration",
               Json(static_cast<double>(op.observed_generation)));
    if (op.finished_at > 0) status.set("finishedAt", Json(op.finished_at));
    Json reps = Json::object();
    for (const auto& rep : op.replicas) {
      Json r = Json::object();
      r.set("phase", Json(phase_name(rep.phase)));
      r.set("restarts", Json(rep.restarts));
      if (rep.exit_code >= 0) r.set("exitCode", Json(rep.exit_code));
      reps.set(rep.pod_name, r);
    }
    status.set("replicaStatuses", reps);
    store_->write_status(op.name, status);
  }
};

}  // namespace ptpu
