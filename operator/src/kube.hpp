// kube-apiserver transport: the operator's in-cluster mode.
//
// Parity target: the reference's Go operator watches Operation CRs and
// creates pods through the Kubernetes API (SURVEY.md 2.14, the
// controller-runtime client).  Ours speaks the same REST surface
// through http.hpp:
//
//   KubeCRStore    — GET  /apis/core.polyaxon-tpu.io/v1/namespaces/NS/
//                         operations          (list, once per tick)
//                    PATCH .../operations/NAME/status   (merge-patch)
//   KubePodRuntime — POST /api/v1/namespaces/NS/pods
//                    GET  /api/v1/namespaces/NS/pods/NAME   (poll phase)
//                    DELETE .../pods/NAME                   (teardown)
//
// Change detection uses metadata.generation (bumped by the apiserver on
// spec writes only), so our own status PATCHes never re-trigger a
// reconcile.  Tested against the stub apiserver
// (polyaxon_tpu/k8s/stub.py) — the envtest analogue: real HTTP, fake
// kubelet.

#pragma once

#include <map>
#include <string>
#include <vector>

#include "http.hpp"
#include "json.hpp"
#include "podruntime.hpp"
#include "reconciler.hpp"

namespace ptpu {

inline const std::string kOperationsGroup = "core.polyaxon-tpu.io";
inline const std::string kOperationsVersion = "v1";

class KubeCRStore : public CRStore {
 public:
  KubeCRStore(HttpClient* http, std::string ns)
      : http_(http), ns_(std::move(ns)) {}

  std::vector<std::string> list() override {
    names_.clear();
    cache_.clear();
    HttpResponse resp = http_->get(ops_path());
    if (!resp.ok()) {
      // Transport blip: report nothing new; the reconciler keeps its
      // current state and retries next tick (a transient apiserver
      // outage must not read as "every CR was deleted").
      return last_names_;
    }
    try {
      Json doc = Json::parse(resp.body);
      for (const auto& item : doc["items"].items()) {
        std::string name = item["metadata"]["name"].as_string();
        names_.push_back(name);
        cache_[name] = item;
      }
    } catch (const std::exception&) {
      return last_names_;
    }
    last_names_ = names_;
    return names_;
  }

  CRRead read(const std::string& name, long known_generation, Json* cr,
              long* generation, std::string* error) override {
    (void)error;
    auto it = cache_.find(name);
    if (it == cache_.end()) return CRRead::NotFound;
    *generation = it->second["metadata"]["generation"].as_int(1);
    if (*generation == known_generation) return CRRead::Unchanged;
    *cr = it->second;
    return CRRead::Updated;
  }

  void write_status(const std::string& name, const Json& status) override {
    Json patch = Json::object();
    patch.set("status", status);
    http_->patch_merge(ops_path() + "/" + name + "/status", patch.dump());
  }

  void clear_status(const std::string& name) override {
    (void)name;  // the CR is gone; there is no status object to clear
  }

  Json prior_status(const std::string& name) override {
    auto it = cache_.find(name);
    return it == cache_.end() ? Json() : it->second["status"];
  }

  std::string log_dir(const std::string& op_name) override {
    (void)op_name;
    return "";  // kubelet owns container logs in-cluster
  }

  bool local_network() const override { return false; }

 private:
  std::string ops_path() const {
    return "/apis/" + kOperationsGroup + "/" + kOperationsVersion +
           "/namespaces/" + ns_ + "/operations";
  }

  HttpClient* http_;
  std::string ns_;
  std::vector<std::string> names_;
  std::vector<std::string> last_names_;
  std::map<std::string, Json> cache_;
};

class KubePodRuntime : public PodRuntime {
 public:
  // cache_ms: age bound on the shared pod LIST used by poll() — one
  // LIST per window serves every replica, instead of a GET per pod per
  // reconcile tick (a 64-replica gang at --poll-ms 100 would otherwise
  // hammer the proxy with ~640 req/s).
  explicit KubePodRuntime(HttpClient* http, long long cache_ms = 50)
      : http_(http), cache_ms_(cache_ms) {}

  int launch(const PodSpec& spec) override {
    int id = next_id_++;
    Pod pod;
    pod.name = spec.name;
    pod.ns = spec.ns;
    Json obj = Json::object();
    obj.set("apiVersion", Json("v1"));
    obj.set("kind", Json("Pod"));
    Json meta = Json::object();
    meta.set("name", Json(spec.name));
    meta.set("namespace", Json(spec.ns));
    if (spec.labels.is_object()) meta.set("labels", spec.labels);
    if (spec.annotations.is_object())
      meta.set("annotations", spec.annotations);
    obj.set("metadata", meta);
    obj.set("spec", with_env(spec.raw_template, spec.extra_env));
    pod.manifest = obj.dump();
    pods_[id] = pod;
    gc_pending_deletes();
    try_create(pods_[id]);
    return id;
  }

  // Operator restart: pick up an already-running pod by name instead of
  // recreating it (reconciler adoption of Running operations).
  int adopt(const PodSpec& spec) override {
    int id = next_id_++;
    Pod pod;
    pod.name = spec.name;
    pod.ns = spec.ns;
    pod.created = true;  // it exists in the cluster; 404 => Failed
    pods_[id] = pod;
    return id;
  }

  PodPhase poll(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return PodPhase::Failed;
    Pod& pod = it->second;
    if (pod.phase == PodPhase::Succeeded || pod.phase == PodPhase::Failed)
      return pod.phase;
    if (!pod.created) {
      // Still waiting out a name collision / transport blip from
      // launch(); keep retrying the POST — unless this pod is being
      // torn down (creating workload during a stop would be wrong).
      if (!pod.deleted) try_create(pod);
      return pod.phase;
    }
    refresh(pod.ns);
    if (!have_list_) return pod.phase;  // no successful LIST yet
    auto entry = list_cache_.find(pod.ns + "/" + pod.name);
    if (entry == list_cache_.end()) {
      // Absent from a successful LIST: deleted out from under us (node
      // drain, chaos) — gang semantics treat that as a failure.
      pod.phase = PodPhase::Failed;
      pod.exit_code = 137;
      return pod.phase;
    }
    pod.phase = entry->second.phase;
    pod.exit_code = entry->second.exit_code;
    return pod.phase;
  }

  int exit_code(int pod_id) override {
    auto it = pods_.find(pod_id);
    return it == pods_.end() ? -1 : it->second.exit_code;
  }

  void terminate_pod(int pod_id) override {
    // DELETE starts the kubelet's own grace period (SIGTERM → grace →
    // SIGKILL), so terminate and kill collapse into one call here.
    kill_pod(pod_id);
  }

  void kill_pod(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return;
    Pod& pod = it->second;
    if (!pod.deleted) delete_pod(pod);
    if (pod.phase == PodPhase::Running || pod.phase == PodPhase::Pending) {
      pod.phase = PodPhase::Failed;
      pod.exit_code = 137;
    }
  }

  void remove(int pod_id) override {
    auto it = pods_.find(pod_id);
    if (it == pods_.end()) return;
    if (!it->second.deleted) delete_pod(it->second);
    pods_.erase(it);
  }

 private:
  struct Pod {
    std::string name;
    std::string ns;
    std::string manifest;  // serialized Pod object for (re)creation
    PodPhase phase = PodPhase::Pending;
    int exit_code = -1;
    bool created = false;
    bool deleted = false;
  };

  struct CachedPhase {
    PodPhase phase = PodPhase::Pending;
    int exit_code = -1;
  };

  // POST the pod; on 409 the name is taken by a prior attempt's pod
  // (DELETE is asynchronous on a real apiserver — the object lingers
  // with a deletionTimestamp through its grace period), so delete it
  // and let poll() retry the POST until the old object is gone.
  // Gang restarts reuse pod names deliberately: stable replica DNS.
  void try_create(Pod& pod) {
    HttpResponse resp = http_->post(pods_path(pod.ns), pod.manifest);
    if (resp.ok()) {
      pod.created = true;
      pod.phase = PodPhase::Pending;
      invalidate_cache();
      return;
    }
    if (resp.status == 409) {
      http_->del(pods_path(pod.ns) + "/" + pod.name);
      pod.phase = PodPhase::Pending;  // retry next poll
      return;
    }
    if (resp.status == 0) {
      pod.phase = PodPhase::Pending;  // transport blip: retry next poll
      return;
    }
    pod.phase = PodPhase::Failed;  // 4xx/5xx: rejected outright
    pod.exit_code = 127;
  }

  // DELETE with failure tracking: a blip must not orphan a running
  // workload holding the TPU slice, so failed deletes queue for retry
  // (drained on every launch/refresh).
  void delete_pod(Pod& pod) {
    HttpResponse resp = http_->del(pods_path(pod.ns) + "/" + pod.name);
    if (resp.ok() || resp.status == 404 || resp.status == 409) {
      pod.deleted = true;
      invalidate_cache();
    } else {
      pending_deletes_.push_back(pods_path(pod.ns) + "/" + pod.name);
      pod.deleted = true;  // ownership handed to the retry queue
    }
  }

  void gc_pending_deletes() {
    std::vector<std::string> still;
    for (const auto& path : pending_deletes_) {
      HttpResponse resp = http_->del(path);
      if (!(resp.ok() || resp.status == 404 || resp.status == 409))
        still.push_back(path);
    }
    pending_deletes_.swap(still);
  }

  void invalidate_cache() { last_list_ms_ = 0; }

  static long long mono_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<long long>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  }

  // One namespace-wide pod LIST per cache window feeds every poll().
  void refresh(const std::string& ns) {
    long long now = mono_ms();
    if (have_list_ && now - last_list_ms_ < cache_ms_) return;
    if (!pending_deletes_.empty()) gc_pending_deletes();
    HttpResponse resp = http_->get(pods_path(ns));
    if (!resp.ok()) return;  // keep the stale cache on blips
    try {
      Json doc = Json::parse(resp.body);
      list_cache_.clear();
      for (const auto& item : doc["items"].items()) {
        const std::string& phase = item["status"]["phase"].as_string();
        CachedPhase entry;
        if (phase == "Running") entry.phase = PodPhase::Running;
        else if (phase == "Succeeded") entry.phase = PodPhase::Succeeded;
        else if (phase == "Failed") entry.phase = PodPhase::Failed;
        else entry.phase = PodPhase::Pending;
        entry.exit_code = terminated_exit_code(item, entry.phase);
        list_cache_[ns + "/" + item["metadata"]["name"].as_string()] =
            entry;
      }
      have_list_ = true;
      last_list_ms_ = now;
    } catch (const std::exception&) {
      // unparseable response: keep the stale cache
    }
  }

  static std::string pods_path(const std::string& ns) {
    return "/api/v1/namespaces/" + ns + "/pods";
  }

  static int terminated_exit_code(const Json& pod, PodPhase phase) {
    for (const auto& cs : pod["status"]["containerStatuses"].items()) {
      const Json& term = cs["state"]["terminated"];
      if (term.is_object() && term.contains("exitCode"))
        return static_cast<int>(term["exitCode"].as_int());
    }
    if (phase == PodPhase::Succeeded) return 0;
    if (phase == PodPhase::Failed) return 1;
    return -1;
  }

  // Merge the reconciler's per-replica env (process ids) into every
  // container of the template — the same contract LocalProcessRuntime
  // gets via ContainerSpec.env.
  static Json with_env(
      const Json& tmpl,
      const std::vector<std::pair<std::string, std::string>>& extra) {
    Json spec = tmpl;
    Json containers = Json::array();
    for (const auto& c : tmpl["containers"].items()) {
      Json out = c;
      Json env = c["env"].is_array() ? c["env"] : Json::array();
      for (const auto& kv : extra) {
        bool replaced = false;
        for (auto& e : env.items())
          if (e["name"].as_string() == kv.first) {
            e.set("value", Json(kv.second));
            replaced = true;
          }
        if (!replaced) {
          Json e = Json::object();
          e.set("name", Json(kv.first));
          e.set("value", Json(kv.second));
          env.push_back(e);
        }
      }
      out.set("env", env);
      containers.push_back(out);
    }
    spec.set("containers", containers);
    if (!spec.contains("restartPolicy"))
      spec.set("restartPolicy", Json("Never"));
    return spec;
  }

  HttpClient* http_;
  long long cache_ms_;
  int next_id_ = 1;
  std::map<int, Pod> pods_;
  std::map<std::string, CachedPhase> list_cache_;
  bool have_list_ = false;
  long long last_list_ms_ = 0;
  std::vector<std::string> pending_deletes_;
};

}  // namespace ptpu
