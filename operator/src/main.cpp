// ptpu-operator: native controller reconciling Operation CRs.
//
// Modes:
//   ptpu-operator --cluster-dir DIR [--poll-ms 100] [--once]
//     File protocol: watches DIR/operations/*.json, runs pods via the
//     local process runtime, writes DIR/status/<name>.json.
//   ptpu-operator --kube-api URL --namespace NS [--token T|--token-file F]
//     API-server transport (VERDICT r1 #7): lists Operation CRs from a
//     kube-apiserver, creates Pod objects, PATCHes /status back.  URL is
//     plaintext http (in-cluster: a kubectl-proxy/localhost sidecar; in
//     tests: the stub apiserver).
//
// SIGTERM/SIGINT drain gracefully (pods killed, statuses flushed).

#include <csignal>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <sstream>
#include <string>
#include <thread>

#include "kube.hpp"
#include "podruntime.hpp"
#include "reconciler.hpp"

static volatile sig_atomic_t g_stop = 0;

static void on_signal(int) { g_stop = 1; }

int main(int argc, char** argv) {
  std::string cluster_dir;
  std::string kube_api;
  std::string ns = "default";
  std::string token;
  int poll_ms = 100;
  int grace_ms = 10000;
  bool once = false;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--cluster-dir" && i + 1 < argc) {
      cluster_dir = argv[++i];
    } else if (arg == "--kube-api" && i + 1 < argc) {
      kube_api = argv[++i];
    } else if (arg == "--namespace" && i + 1 < argc) {
      ns = argv[++i];
    } else if (arg == "--token" && i + 1 < argc) {
      token = argv[++i];
    } else if (arg == "--token-file" && i + 1 < argc) {
      std::ifstream f(argv[++i]);
      std::ostringstream ss;
      ss << f.rdbuf();
      token = ss.str();
      while (!token.empty() &&
             (token.back() == '\n' || token.back() == '\r'))
        token.pop_back();
    } else if (arg == "--poll-ms" && i + 1 < argc) {
      poll_ms = std::atoi(argv[++i]);
    } else if (arg == "--grace-ms" && i + 1 < argc) {
      grace_ms = std::atoi(argv[++i]);
    } else if (arg == "--once") {
      once = true;
    } else if (arg == "--help") {
      std::cout << "ptpu-operator --cluster-dir DIR [--poll-ms N]"
                   " [--grace-ms N] [--once]\n"
                   "ptpu-operator --kube-api URL [--namespace NS]"
                   " [--token T | --token-file F] [--poll-ms N] [--once]\n";
      return 0;
    } else {
      std::cerr << "unknown arg: " << arg << "\n";
      return 2;
    }
  }
  if (cluster_dir.empty() == kube_api.empty()) {
    std::cerr << "exactly one of --cluster-dir / --kube-api is required\n";
    return 2;
  }

  std::signal(SIGTERM, on_signal);
  std::signal(SIGINT, on_signal);

  std::unique_ptr<ptpu::HttpClient> http;
  std::unique_ptr<ptpu::CRStore> store;
  std::unique_ptr<ptpu::PodRuntime> runtime;
  std::unique_ptr<ptpu::Reconciler> reconciler;

  if (!kube_api.empty()) {
    try {
      http = std::make_unique<ptpu::HttpClient>(kube_api, token);
    } catch (const std::exception& e) {
      std::cerr << "bad --kube-api: " << e.what() << "\n";
      return 2;
    }
    store = std::make_unique<ptpu::KubeCRStore>(http.get(), ns);
    runtime = std::make_unique<ptpu::KubePodRuntime>(http.get());
    reconciler =
        std::make_unique<ptpu::Reconciler>(store.get(), runtime.get());
  } else {
    runtime = std::make_unique<ptpu::LocalProcessRuntime>(grace_ms);
    reconciler =
        std::make_unique<ptpu::Reconciler>(cluster_dir, runtime.get());
  }

  do {
    reconciler->tick();
    if (once) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(poll_ms));
  } while (!g_stop);

  return 0;
}
