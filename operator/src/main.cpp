// ptpu-operator: native controller reconciling Operation CRs.
//
// Usage: ptpu-operator --cluster-dir DIR [--poll-ms 100] [--once]
//
// Watches DIR/operations/*.json, runs pods via the local process
// runtime, writes DIR/status/<name>.json.  SIGTERM/SIGINT drain
// gracefully (pods killed, statuses flushed).

#include <csignal>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <string>
#include <thread>

#include "podruntime.hpp"
#include "reconciler.hpp"

static volatile sig_atomic_t g_stop = 0;

static void on_signal(int) { g_stop = 1; }

int main(int argc, char** argv) {
  std::string cluster_dir;
  int poll_ms = 100;
  int grace_ms = 10000;
  bool once = false;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--cluster-dir" && i + 1 < argc) {
      cluster_dir = argv[++i];
    } else if (arg == "--poll-ms" && i + 1 < argc) {
      poll_ms = std::atoi(argv[++i]);
    } else if (arg == "--grace-ms" && i + 1 < argc) {
      grace_ms = std::atoi(argv[++i]);
    } else if (arg == "--once") {
      once = true;
    } else if (arg == "--help") {
      std::cout << "ptpu-operator --cluster-dir DIR [--poll-ms N]"
                   " [--grace-ms N] [--once]\n";
      return 0;
    } else {
      std::cerr << "unknown arg: " << arg << "\n";
      return 2;
    }
  }
  if (cluster_dir.empty()) {
    std::cerr << "--cluster-dir is required\n";
    return 2;
  }

  std::signal(SIGTERM, on_signal);
  std::signal(SIGINT, on_signal);

  ptpu::LocalProcessRuntime runtime(grace_ms);
  ptpu::Reconciler reconciler(cluster_dir, &runtime);

  do {
    reconciler.tick();
    if (once) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(poll_ms));
  } while (!g_stop);

  return 0;
}
