// Minimal blocking HTTP/1.1 client over POSIX sockets.
//
// Transport for the operator's kube-apiserver mode (SURVEY.md 2.14: the
// reference operator talks to the API server through client-go; ours
// speaks the same REST surface directly).  Plaintext only: in-cluster
// the operator sits behind `kubectl proxy`/a localhost sidecar, and the
// test harness is the stub apiserver (polyaxon_tpu/k8s/stub.py).
// Handles Content-Length and chunked responses; one connection per
// request (the apiserver keeps-alive, but reconnect-per-poll keeps the
// failure model trivial and the poll rate is ~10 Hz).

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace ptpu {

struct HttpResponse {
  int status = 0;           // 0 = transport error
  std::string body;
  std::string error;        // transport-level failure description
  bool ok() const { return status >= 200 && status < 300; }
};

class HttpClient {
 public:
  // base_url: "http://host:port" (optionally with a path prefix).
  explicit HttpClient(const std::string& base_url,
                      std::string bearer_token = "",
                      int timeout_ms = 5000)
      : token_(std::move(bearer_token)), timeout_ms_(timeout_ms) {
    std::string rest = base_url;
    const std::string scheme = "http://";
    if (rest.rfind(scheme, 0) == 0) rest = rest.substr(scheme.size());
    size_t slash = rest.find('/');
    std::string hostport = rest.substr(0, slash);
    if (slash != std::string::npos) prefix_ = rest.substr(slash);
    size_t colon = hostport.rfind(':');
    if (colon != std::string::npos) {
      host_ = hostport.substr(0, colon);
      std::string port_str = hostport.substr(colon + 1);
      try {
        size_t used = 0;
        port_ = std::stoi(port_str, &used);
        if (used != port_str.size() || port_ <= 0 || port_ > 65535)
          throw std::invalid_argument(port_str);
      } catch (const std::exception&) {
        // Surface a usage error, not std::terminate (a malformed
        // --kube-api in a pod spec would otherwise CrashLoopBackOff
        // with an opaque abort).
        throw std::runtime_error("invalid port in URL: " + base_url);
      }
    } else {
      host_ = hostport;
      port_ = 80;
    }
    if (host_.empty())
      throw std::runtime_error("invalid URL (no host): " + base_url);
  }

  HttpResponse get(const std::string& path) {
    return request("GET", path, "", "");
  }
  HttpResponse post(const std::string& path, const std::string& body) {
    return request("POST", path, body, "application/json");
  }
  HttpResponse patch_merge(const std::string& path,
                           const std::string& body) {
    return request("PATCH", path, body, "application/merge-patch+json");
  }
  HttpResponse del(const std::string& path) {
    return request("DELETE", path, "", "");
  }

  HttpResponse request(const std::string& method, const std::string& path,
                       const std::string& body,
                       const std::string& content_type) {
    HttpResponse resp;
    int fd = connect_socket(resp);
    if (fd < 0) return resp;

    std::string req = method + " " + prefix_ + path + " HTTP/1.1\r\n";
    req += "Host: " + host_ + ":" + std::to_string(port_) + "\r\n";
    req += "Accept: application/json\r\n";
    req += "Connection: close\r\n";
    if (!token_.empty()) req += "Authorization: Bearer " + token_ + "\r\n";
    if (!content_type.empty())
      req += "Content-Type: " + content_type + "\r\n";
    req += "Content-Length: " + std::to_string(body.size()) + "\r\n\r\n";
    req += body;

    size_t sent = 0;
    while (sent < req.size()) {
      ssize_t n = ::send(fd, req.data() + sent, req.size() - sent, 0);
      if (n <= 0) {
        resp.error = "send failed";
        ::close(fd);
        return resp;
      }
      sent += static_cast<size_t>(n);
    }

    std::string raw;
    char buf[8192];
    for (;;) {
      ssize_t n = ::recv(fd, buf, sizeof buf, 0);
      if (n < 0) {
        resp.error = "recv failed";
        ::close(fd);
        return resp;
      }
      if (n == 0) break;
      raw.append(buf, static_cast<size_t>(n));
    }
    ::close(fd);
    parse(raw, &resp);
    return resp;
  }

 private:
  int connect_socket(HttpResponse& resp) {
    struct addrinfo hints {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host_.c_str(), std::to_string(port_).c_str(), &hints,
                    &res) != 0 || res == nullptr) {
      resp.error = "resolve failed: " + host_;
      return -1;
    }
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) {
      freeaddrinfo(res);
      resp.error = "socket failed";
      return -1;
    }
    struct timeval tv {};
    tv.tv_sec = timeout_ms_ / 1000;
    tv.tv_usec = (timeout_ms_ % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      resp.error = "connect failed: " + host_ + ":" +
                   std::to_string(port_);
      ::close(fd);
      freeaddrinfo(res);
      return -1;
    }
    freeaddrinfo(res);
    return fd;
  }

  static void parse(const std::string& raw, HttpResponse* resp) {
    size_t header_end = raw.find("\r\n\r\n");
    if (header_end == std::string::npos) {
      resp->error = "malformed response";
      return;
    }
    size_t line_end = raw.find("\r\n");
    std::string status_line = raw.substr(0, line_end);
    size_t sp = status_line.find(' ');
    if (sp != std::string::npos)
      resp->status = std::atoi(status_line.c_str() + sp + 1);

    std::string headers = raw.substr(0, header_end);
    std::string body = raw.substr(header_end + 4);
    // lowercase header scan for transfer-encoding: chunked
    std::string lower;
    lower.reserve(headers.size());
    for (char c : headers)
      lower += static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
    if (lower.find("transfer-encoding: chunked") != std::string::npos) {
      resp->body = dechunk(body);
    } else {
      resp->body = body;  // Connection: close → body runs to EOF
    }
  }

  static std::string dechunk(const std::string& body) {
    std::string out;
    size_t pos = 0;
    while (pos < body.size()) {
      size_t crlf = body.find("\r\n", pos);
      if (crlf == std::string::npos) break;
      long len = std::strtol(body.c_str() + pos, nullptr, 16);
      if (len <= 0) break;
      pos = crlf + 2;
      if (pos + static_cast<size_t>(len) > body.size()) break;
      out.append(body, pos, static_cast<size_t>(len));
      pos += static_cast<size_t>(len) + 2;  // skip trailing CRLF
    }
    return out;
  }

  std::string host_;
  int port_ = 80;
  std::string prefix_;
  std::string token_;
  int timeout_ms_;
};

}  // namespace ptpu
